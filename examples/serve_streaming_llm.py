"""Stream REAL model tokens through Serve — handle, HTTP SSE, gRPC.

The flagship TPU serving pattern: the continuous-batching LLM engine
(ray_tpu.serve.llm — paged KV cache + bucketed prefill/decode scheduling)
runs LlamaConfig.tiny() inside a Serve replica and streams one token per
decode step through three ingress paths — the in-process DeploymentHandle,
the HTTP proxy as server-sent events, and the gRPC ingress's
server-streaming RPC. Greedy decoding makes the three paths token-exact
replicas of each other.

Run: python examples/serve_streaming_llm.py
"""
import json
import urllib.request

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.llm import EngineConfig, build_llm_app

HTTP_PORT = 18411
PROMPT = "hello"
N_TOKENS = 8


def main():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    serve.start(http_options={"port": HTTP_PORT}, grpc_options={"port": 0})

    # LlamaConfig.tiny() by default; a larger model is EngineConfig(
    #   model_config=LlamaConfig(...), num_blocks=..., block_size=32)
    app = build_llm_app(EngineConfig(model="llama", seed=0))
    handle = serve.run(app, name="llm", route_prefix="/llm")
    payload = {"prompt": PROMPT, "max_new_tokens": N_TOKENS}

    # 1. handle: iterate the DeploymentResponseGenerator
    tokens = [c["token"] for c in handle.remote(payload)]
    print("handle stream:", tokens)

    # 2. HTTP: server-sent events
    req = urllib.request.Request(
        f"http://127.0.0.1:{HTTP_PORT}/llm",
        data=json.dumps(payload).encode(),
        headers={"Accept": "text/event-stream"},
    )
    sse = []
    with urllib.request.urlopen(req, timeout=120) as resp:
        for line in resp:
            if line.startswith(b"data: "):
                sse.append(json.loads(line[6:])["token"])
    print("SSE stream:", sse)

    # 3. gRPC: server-streaming RPC on the generic ServeAPI service
    import grpc

    ch = grpc.insecure_channel(f"127.0.0.1:{serve.grpc_port()}")
    stream = ch.unary_stream(
        "/ray_tpu.serve.ServeAPI/Stream",
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )
    rpc = [json.loads(c)["result"]["token"]
           for c in stream(json.dumps(payload).encode(),
                           metadata=(("application", "llm"),), timeout=120)]
    ch.close()
    print("gRPC stream:", rpc)

    # greedy decode: every ingress path must produce the same real tokens
    assert len(tokens) == N_TOKENS
    assert sse == tokens
    assert rpc == tokens
    serve.shutdown()
    return tokens, sse, rpc


if __name__ == "__main__":
    main()
