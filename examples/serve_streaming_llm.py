"""Stream LLM-style token decode through Serve — handle, HTTP SSE, gRPC.

The flagship TPU serving pattern (reference: serve streaming responses,
doc/source/serve/tutorials/streaming): a generator deployment yields one
token at a time; the chunks reach the client AS PRODUCED through three
ingress paths — the in-process DeploymentHandle, the HTTP proxy as
server-sent events, and the gRPC ingress's server-streaming RPC.

Run: python examples/serve_streaming_llm.py
"""
import json
import time
import urllib.request

import ray_tpu
from ray_tpu import serve

HTTP_PORT = 18411


def main():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    serve.start(http_options={"port": HTTP_PORT}, grpc_options={"port": 0})

    @serve.deployment(num_replicas=1)
    class Decoder:
        """Stand-in for a jitted decode loop: one token per step."""

        def __call__(self, payload):
            prompt = (payload or {}).get("prompt", "")
            for i, word in enumerate(f"echo:{prompt}".split(":")):
                yield {"token": word, "index": i}
                time.sleep(0.05)

    handle = serve.run(Decoder.bind(), name="llm", route_prefix="/llm")

    # 1. handle: iterate the DeploymentResponseGenerator
    tokens = [c["token"] for c in handle.remote({"prompt": "hello"})]
    print("handle stream:", tokens)

    # 2. HTTP: server-sent events
    req = urllib.request.Request(
        f"http://127.0.0.1:{HTTP_PORT}/llm",
        data=json.dumps({"prompt": "world"}).encode(),
        headers={"Accept": "text/event-stream"},
    )
    sse = []
    with urllib.request.urlopen(req, timeout=60) as resp:
        for line in resp:
            if line.startswith(b"data: "):
                sse.append(json.loads(line[6:])["token"])
    print("SSE stream:", sse)

    # 3. gRPC: server-streaming RPC on the generic ServeAPI service
    import grpc

    ch = grpc.insecure_channel(f"127.0.0.1:{serve.grpc_port()}")
    stream = ch.unary_stream(
        "/ray_tpu.serve.ServeAPI/Stream",
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )
    rpc = [json.loads(c)["result"]["token"]
           for c in stream(json.dumps({"prompt": "grpc"}).encode(),
                           metadata=(("application", "llm"),), timeout=60)]
    ch.close()
    print("gRPC stream:", rpc)

    assert tokens == ["echo", "hello"]
    assert sse == ["echo", "world"]
    assert rpc == ["echo", "grpc"]
    serve.shutdown()
    return tokens, sse, rpc


if __name__ == "__main__":
    main()
