"""Train a GPT on a device mesh with JaxTrainer.

Mirrors the reference's data-parallel trainer quickstart
(doc/source/train/getting-started) on the TPU-native stack: ScalingConfig
picks the gang, the train loop builds a mesh, shards params by the logical
axis table, and reports through the session.

Run small (CPU mesh):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/train_gpt_mesh.py
"""
import os

import ray_tpu
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

TINY = os.environ.get("EXAMPLE_TINY", "1") == "1"


def train_loop(config):
    import os

    import jax

    # Workers are fresh processes and must match the DRIVER's platform
    # decision, not the ambient env: a driver that runs on the CPU mesh
    # passes force_cpu so workers never probe the accelerator (on a TPU
    # host with a wedged tunnel, backend discovery can hang a worker
    # forever — the env var alone doesn't capture an in-process
    # jax.config.update("jax_platforms", "cpu") in the driver).
    if config.get("force_cpu") or (
            os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"):
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.gpt import (
        GPTConfig, gpt_init, gpt_loss, gpt_param_axes,
    )
    from ray_tpu.parallel import (
        MeshSpec, ShardingRules, build_mesh, shard_params,
    )
    from ray_tpu.train import session

    cfg = GPTConfig.tiny() if config["tiny"] else GPTConfig.gpt2_small()
    mesh = build_mesh(MeshSpec(dp=-1))  # all local devices on the data axis
    rules = ShardingRules()
    params = shard_params(
        gpt_init(jax.random.PRNGKey(0), cfg), gpt_param_axes(cfg), mesh, rules
    )
    tx = optax.adamw(3e-4)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(gpt_loss)(
            params, batch, cfg, rules=rules, mesh=mesh
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    key = jax.random.PRNGKey(1)
    tokens = jax.random.randint(key, (8, 65), 0, cfg.vocab_size)
    for i in range(config["steps"]):
        params, opt_state, loss = step(params, opt_state, {"tokens": tokens})
        if i % 5 == 0 or i == config["steps"] - 1:
            session.report({"step": i, "loss": float(loss)})


def main():
    import sys

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    # propagate the driver's platform to the gang: if jax is already up on
    # CPU here (tests force it; JAX_PLATFORMS=cpu runs force it), workers
    # must not initialize an accelerator backend
    force_cpu = False
    if "jax" in sys.modules:
        import jax

        # only an EXPLICIT cpu-only platform config counts: the unset
        # default (None) means "use the accelerator", and forcing workers
        # to CPU then would silently de-accelerate real training
        plat = jax.config.jax_platforms or ""
        force_cpu = bool(plat) and set(plat.split(",")) == {"cpu"}
    trainer = JaxTrainer(
        train_loop,
        train_loop_config={"tiny": TINY, "steps": 20 if TINY else 200,
                           "force_cpu": force_cpu},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="gpt-example"),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    print("final:", result.metrics)
    return result


if __name__ == "__main__":
    main()
