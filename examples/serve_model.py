"""Serve a jitted model with shape-bucketed batching.

Mirrors the reference's serve quickstart (doc/source/serve/getting_started):
a deployment with replica-side dynamic batching whose buckets keep the
jitted function recompile-free, exercised through a DeploymentHandle.

Run: python examples/serve_model.py
"""
import numpy as np

import ray_tpu
from ray_tpu import serve


def main():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)

    @serve.deployment(num_replicas=1)
    class Doubler:
        def __init__(self):
            self.calls = 0

        @serve.batch(max_batch_size=8, size_buckets=(2, 4, 8),
                     batch_wait_timeout_s=0.02)
        def __call__(self, items):
            self.calls += 1
            return [np.asarray(x) * 2 for x in items]

    handle = serve.run(Doubler.bind(), name="doubler")
    futures = [handle.remote(np.full(3, i)) for i in range(10)]
    outs = [f.result(timeout=60) for f in futures]
    print("served:", [int(o[0]) for o in outs])
    assert [int(o[0]) for o in outs] == [0, 2, 4, 6, 8, 10, 12, 14, 16, 18]
    serve.shutdown()
    return outs


if __name__ == "__main__":
    main()
