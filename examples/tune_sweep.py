"""Hyperparameter sweep: native TPE searcher under ASHA early stopping.

Mirrors the reference's tune quickstart (doc/source/tune/getting_started)
with the in-tree BOHB-style composition (model-based suggestions + ASHA).

Run: python examples/tune_sweep.py
"""
import ray_tpu
from ray_tpu import tune
from ray_tpu.tune.schedulers import ASHAScheduler


def objective(config):
    x, lr = config["x"], config["lr"]
    for i in range(5):
        # pretend training: best at x=0.3, lr=1e-2
        score = -((x - 0.3) ** 2) - abs(lr - 1e-2) * 10 - 0.01 * (5 - i)
        tune.report({"score": score})


def main():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    tuner = tune.Tuner(
        objective,
        tune_config=tune.TuneConfig(
            metric="score",
            mode="max",
            search_alg=tune.TPESearcher(
                {"x": tune.uniform(0, 1), "lr": tune.loguniform(1e-4, 1e-1)},
                n_startup=5, max_trials=15, seed=0,
            ),
            scheduler=ASHAScheduler(max_t=5, grace_period=1),
            max_concurrent_trials=2,
        ),
        run_config=tune.TuneRunConfig(name="tpe-example"),
    )
    results = tuner.fit()
    best = results.get_best_result()
    print("best config:", best.config, "score:", best.metrics["score"])
    assert abs(best.config["x"] - 0.3) < 0.5
    return best


if __name__ == "__main__":
    main()
