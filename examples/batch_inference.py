"""Batch inference over a Dataset with a stateful actor pool.

Mirrors the reference's batch-inference quickstart (doc/source/data/
batch_inference): a model class constructed once per pool actor, blocks
streamed through `map_batches(..., compute=ActorPoolStrategy(...))`.

Run: python examples/batch_inference.py
"""
import numpy as np

import ray_tpu
from ray_tpu import data
from ray_tpu.data import ActorPoolStrategy


def main():
    # explicit CPUs: the actor pool RESERVES one per actor, and upstream
    # read tasks still need slots to run (on a 1-CPU host an actor pool
    # would otherwise starve its own input)
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)

    class Model:
        """Stands in for an expensive checkpoint load (once per actor)."""

        def __init__(self, scale):
            self.w = np.full(8, scale, np.float32)

        def __call__(self, batch):
            x = np.stack([batch["data"][i] for i in range(len(batch["data"]))])
            return {"pred": (x * self.w).sum(axis=1)}

    ds = (
        data.range_tensor(64, shape=(8,))
        .map_batches(
            Model,
            fn_constructor_args=(0.5,),
            batch_size=16,
            compute=ActorPoolStrategy(min_size=1, max_size=2),
        )
    )
    preds = [r["pred"] for r in ds.take_all()]
    print("rows:", len(preds), "first:", preds[0])
    assert len(preds) == 64
    return preds


if __name__ == "__main__":
    main()
