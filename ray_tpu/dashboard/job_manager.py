"""JobManager — run submitted entrypoints as driver subprocesses.

Equivalent of the reference's JobManager
(reference: dashboard/modules/job/job_manager.py — drivers run as
subprocesses on the cluster with RAY_ADDRESS set; status + logs tracked per
job). Submitted entrypoints get RT_ADDRESS so `ray_tpu.init(address="auto")`
connects them to this cluster.
"""
from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid


PENDING, RUNNING, SUCCEEDED, FAILED, STOPPED = (
    "PENDING", "RUNNING", "SUCCEEDED", "FAILED", "STOPPED",
)


class JobManager:
    def __init__(self, gcs_address: str, log_dir: str):
        self.gcs_address = gcs_address
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._jobs: dict[str, dict] = {}

    def submit(
        self,
        entrypoint: str,
        *,
        submission_id: str | None = None,
        env: dict[str, str] | None = None,
        cwd: str | None = None,
    ) -> str:
        job_id = submission_id or f"rtjob-{uuid.uuid4().hex[:10]}"
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id!r} already exists")
        log_path = os.path.join(self.log_dir, f"{job_id}.log")
        full_env = dict(os.environ)
        full_env.update(env or {})
        full_env["RT_ADDRESS"] = self.gcs_address
        full_env["RT_JOB_ID"] = job_id
        log_f = open(log_path, "wb")
        proc = subprocess.Popen(
            entrypoint, shell=True, env=full_env, cwd=cwd,
            stdout=log_f, stderr=subprocess.STDOUT,
        )
        with self._lock:
            self._jobs[job_id] = {
                "job_id": job_id,
                "entrypoint": entrypoint,
                "proc": proc,
                "log_file": log_f,
                "log_path": log_path,
                "status": RUNNING,
                "start_time": time.time(),
                "end_time": None,
            }
        return job_id

    def _refresh(self, j: dict) -> None:
        proc = j["proc"]
        if j["status"] == RUNNING and proc is not None:
            rc = proc.poll()
            if rc is not None:
                j["status"] = SUCCEEDED if rc == 0 else FAILED
                j["end_time"] = time.time()
                j["log_file"].close()

    def status(self, job_id: str) -> dict:
        with self._lock:
            j = self._jobs.get(job_id)
            if j is None:
                raise KeyError(job_id)
            self._refresh(j)
            return {
                k: j[k]
                for k in ("job_id", "entrypoint", "status", "start_time", "end_time")
            }

    def logs(self, job_id: str) -> str:
        with self._lock:
            j = self._jobs.get(job_id)
            if j is None:
                raise KeyError(job_id)
            path = j["log_path"]
        try:
            with open(path, "r", errors="replace") as f:
                return f.read()
        except FileNotFoundError:
            return ""

    def stop(self, job_id: str) -> bool:
        with self._lock:
            j = self._jobs.get(job_id)
            if j is None:
                raise KeyError(job_id)
            proc = j["proc"]
            if j["status"] != RUNNING or proc.poll() is not None:
                return False
        proc.terminate()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            proc.kill()  # SIGTERM trapped — escalate so STOPPED means stopped
            proc.wait(timeout=5)
        with self._lock:
            j["status"] = STOPPED
            j["end_time"] = time.time()
            j["log_file"].close()
        return True

    def list(self) -> list[dict]:
        with self._lock:
            out = []
            for j in self._jobs.values():
                self._refresh(j)
                out.append(
                    {
                        k: j[k]
                        for k in (
                            "job_id", "entrypoint", "status",
                            "start_time", "end_time",
                        )
                    }
                )
            return out

    def wait(self, job_id: str, timeout: float = 300.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            st = self.status(job_id)["status"]
            if st in (SUCCEEDED, FAILED, STOPPED):
                return st
            time.sleep(0.2)
        raise TimeoutError(f"job {job_id} still running after {timeout}s")
