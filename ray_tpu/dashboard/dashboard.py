"""Dashboard: REST backend for cluster state + job submission.

Equivalent of the reference's dashboard head REST surface
(reference: dashboard/head.py + module system dashboard/modules/* — node,
actor, state, job REST endpoints; job REST dashboard/modules/job/job_head.py).
The reference's React client is UI-only and out of scope; every endpoint
here returns JSON suitable for curl/CLI consumption.
"""
from __future__ import annotations

import asyncio
import json
import threading

from ray_tpu.dashboard.job_manager import JobManager


class Dashboard:
    def __init__(self, gcs_address: str, host: str = "127.0.0.1", port: int = 8265,
                 log_dir: str | None = None):
        import tempfile

        self.gcs_address = gcs_address
        self.host = host
        self.port = port
        self.jobs = JobManager(
            gcs_address, log_dir or tempfile.mkdtemp(prefix="rt_job_logs_")
        )
        self._loop = None
        self._started = threading.Event()
        self._start_error: Exception | None = None

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "Dashboard":
        t = threading.Thread(target=self._serve, daemon=True, name="dashboard")
        t.start()
        if not self._started.wait(15):
            raise RuntimeError("dashboard failed to start")
        if self._start_error:
            raise self._start_error
        return self

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)

    # -- server --

    def _serve(self) -> None:
        from aiohttp import web

        import ray_tpu
        from ray_tpu.util import state

        def offload(fn, *args):
            return asyncio.get_event_loop().run_in_executor(None, fn, *args)

        async def nodes(request):
            return web.json_response({"nodes": await offload(state.list_nodes)})

        async def actors(request):
            return web.json_response({"actors": await offload(state.list_actors)})

        async def tasks(request):
            return web.json_response({"tasks": await offload(state.list_tasks)})

        async def cluster(request):
            return web.json_response(await offload(state.summary))

        async def submit_job(request):
            body = await request.json()
            try:
                job_id = await offload(
                    lambda: self.jobs.submit(
                        body["entrypoint"],
                        submission_id=body.get("submission_id"),
                        env=body.get("env"),
                        cwd=body.get("cwd"),
                    )
                )
            except ValueError as e:
                return web.json_response({"error": str(e)}, status=400)
            return web.json_response({"job_id": job_id})

        async def list_jobs(request):
            return web.json_response({"jobs": await offload(self.jobs.list)})

        async def job_status(request):
            try:
                st = await offload(self.jobs.status, request.match_info["job_id"])
            except KeyError:
                return web.json_response({"error": "no such job"}, status=404)
            return web.json_response(st)

        async def job_logs(request):
            try:
                logs = await offload(self.jobs.logs, request.match_info["job_id"])
            except KeyError:
                return web.json_response({"error": "no such job"}, status=404)
            return web.json_response({"logs": logs})

        async def stop_job(request):
            try:
                stopped = await offload(self.jobs.stop, request.match_info["job_id"])
            except KeyError:
                return web.json_response({"error": "no such job"}, status=404)
            return web.json_response({"stopped": stopped})

        def _controller():
            from ray_tpu.serve.controller import CONTROLLER_NAME

            return ray_tpu.get_actor(CONTROLLER_NAME)

        def _fleet_metrics():
            ctrl = _controller()
            return ray_tpu.get(ctrl.fleet_metrics.remote(), timeout=30)

        def _fleet_history(series, prefix):
            ctrl = _controller()
            return ray_tpu.get(
                ctrl.fleet_history.remote(series, prefix), timeout=30
            )

        async def fleet_metrics_text(request):
            """THE fleet scrape target: Prometheus text exposition of
            every replica/proxy/controller series, relabeled and rolled
            up by the controller's FleetAggregator."""
            try:
                out = await offload(_fleet_metrics)
            except Exception as e:  # noqa: BLE001 — no controller yet
                return web.json_response(
                    {"error": f"serve controller unavailable: {e}"},
                    status=503,
                )
            return web.Response(
                text=out["text"],
                content_type="text/plain",
                charset="utf-8",
            )

        async def fleet_metrics_json(request):
            try:
                out = await offload(_fleet_metrics)
            except Exception as e:  # noqa: BLE001
                return web.json_response(
                    {"error": f"serve controller unavailable: {e}"},
                    status=503,
                )
            return web.json_response(
                {"families": out["families"], "sources": out["sources"]}
            )

        async def fleet_history(request):
            """Ring-buffer time series behind the scrape target:
            ``?series=<exact key>`` or ``?prefix=<name prefix>`` —
            queryable after the source replica is gone."""
            series = request.query.get("series")
            prefix = request.query.get("prefix")
            try:
                hist = await offload(_fleet_history, series, prefix)
            except Exception as e:  # noqa: BLE001
                return web.json_response(
                    {"error": f"serve controller unavailable: {e}"},
                    status=503,
                )
            return web.json_response({"series": hist})

        async def traces(request):
            """Tail-sampled fleet traces held by the controller's
            TraceStore: ``?app=``, ``?status=`` (a retention flag, or
            ``slow``/``sampled``), ``?min_duration_s=``, ``?limit=``."""
            q = request.query

            def _list():
                ctrl = _controller()
                return ray_tpu.get(ctrl.trace_list.remote(
                    app=q.get("app"), status=q.get("status"),
                    min_duration_s=(float(q["min_duration_s"])
                                    if "min_duration_s" in q else None),
                    limit=int(q.get("limit", 100)),
                ), timeout=30)

            try:
                out = await offload(_list)
            except Exception as e:  # noqa: BLE001
                return web.json_response(
                    {"error": f"serve controller unavailable: {e}"},
                    status=503,
                )
            return web.json_response({"traces": out})

        def _trace_call(method, trace_id):
            ctrl = _controller()
            return ray_tpu.get(
                getattr(ctrl, method).remote(trace_id), timeout=30)

        async def trace_get(request):
            """One assembled trace tree — spans from every process the
            request touched (proxy, router, prefill, decode), nested."""
            try:
                out = await offload(
                    _trace_call, "trace_get", request.match_info["trace_id"])
            except Exception as e:  # noqa: BLE001
                return web.json_response(
                    {"error": f"serve controller unavailable: {e}"},
                    status=503,
                )
            if out is None:
                return web.json_response(
                    {"error": "no such trace"}, status=404)
            return web.json_response(out, dumps=lambda o: json.dumps(
                o, default=str))

        async def trace_chrome(request):
            """The same trace rendered as chrome://tracing events (load
            in Perfetto / chrome://tracing), one pid per source process."""
            from ray_tpu.util import tracing

            try:
                spans = await offload(
                    _trace_call, "trace_spans",
                    request.match_info["trace_id"])
            except Exception as e:  # noqa: BLE001
                return web.json_response(
                    {"error": f"serve controller unavailable: {e}"},
                    status=503,
                )
            if not spans:
                return web.json_response(
                    {"error": "no such trace"}, status=404)
            return web.json_response(
                {"traceEvents": tracing.spans_to_chrome(spans)})

        async def slo(request):
            """Burn-rate state of every declared SLO, with exemplar
            trace ids for the ones currently burning."""

            def _slo():
                ctrl = _controller()
                return ray_tpu.get(ctrl.slo_status.remote(), timeout=30)

            try:
                out = await offload(_slo)
            except Exception as e:  # noqa: BLE001
                return web.json_response(
                    {"error": f"serve controller unavailable: {e}"},
                    status=503,
                )
            return web.json_response(out)

        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        app = web.Application()
        app.router.add_get("/api/nodes", nodes)
        app.router.add_get("/api/actors", actors)
        app.router.add_get("/api/tasks", tasks)
        app.router.add_get("/api/cluster_status", cluster)
        app.router.add_post("/api/jobs", submit_job)
        app.router.add_get("/api/jobs", list_jobs)
        app.router.add_get("/api/jobs/{job_id}", job_status)
        app.router.add_get("/api/jobs/{job_id}/logs", job_logs)
        app.router.add_post("/api/jobs/{job_id}/stop", stop_job)
        app.router.add_get("/metrics/fleet", fleet_metrics_text)
        app.router.add_get("/api/metrics/fleet", fleet_metrics_json)
        app.router.add_get("/api/metrics/fleet/history", fleet_history)
        app.router.add_get("/api/traces", traces)
        app.router.add_get("/api/traces/{trace_id}", trace_get)
        app.router.add_get("/api/traces/{trace_id}/chrome", trace_chrome)
        app.router.add_get("/api/slo", slo)
        runner = web.AppRunner(app)
        try:
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, self.host, self.port)
            loop.run_until_complete(site.start())
        except Exception as e:  # noqa: BLE001
            self._start_error = e
            self._started.set()
            return
        self._started.set()
        loop.run_forever()
        loop.run_until_complete(runner.cleanup())


def start_dashboard(gcs_address: str | None = None, port: int = 8265) -> Dashboard:
    """Start the dashboard against the current (or given) cluster."""
    if gcs_address is None:
        import ray_tpu

        gcs_address = ray_tpu.worker.global_worker().gcs.address
    return Dashboard(gcs_address, port=port).start()
