from ray_tpu.dashboard.dashboard import Dashboard, start_dashboard

__all__ = ["Dashboard", "start_dashboard"]
