from ray_tpu.ops.attention import flash_attention, mha_reference
from ray_tpu.ops.layers import gelu, layer_norm, rms_norm, rope, rope_cache

__all__ = [
    "flash_attention",
    "mha_reference",
    "gelu",
    "layer_norm",
    "rms_norm",
    "rope",
    "rope_cache",
]
