from ray_tpu.ops.attention import flash_attention, mha_reference
from ray_tpu.ops.kv_cache import (
    copy_blocks,
    gather_kv,
    paged_attention,
    paged_prefill_attention,
    physical_slots,
    write_kv,
)
from ray_tpu.ops.layers import gelu, layer_norm, rms_norm, rope, rope_cache
from ray_tpu.ops.paged_attention import (
    decode_attention,
    paged_attention_pallas,
    paged_prefill_attention_pallas,
    prefill_attention,
)

__all__ = [
    "flash_attention",
    "mha_reference",
    "gelu",
    "layer_norm",
    "rms_norm",
    "rope",
    "rope_cache",
    # paged-KV primitives (kv_cache.py)
    "write_kv",
    "gather_kv",
    "copy_blocks",
    "physical_slots",
    "paged_attention",
    "paged_prefill_attention",
    # fused decode/prefill kernels + backend dispatchers (paged_attention.py)
    "paged_attention_pallas",
    "decode_attention",
    "paged_prefill_attention_pallas",
    "prefill_attention",
]
