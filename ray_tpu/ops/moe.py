"""Mixture-of-Experts layer with expert parallelism over the `ep` mesh axis.

The reference has NO MoE / expert parallelism (SURVEY.md §2.4 EP row:
absent) — new first-class capability, built the TPU way: top-k gating with
capacity-bounded one-hot dispatch einsums (static shapes — no ragged
gather), experts sharded on the `ep` axis; under jit the dispatch/combine
einsums against ep-sharded expert weights lower to XLA all-to-alls on ICI.

Math follows the public Switch/GShard formulation: router softmax → top-k
experts per token → capacity-truncated dispatch mask → expert MLPs →
gate-weighted combine, plus the standard load-balancing auxiliary loss.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from ray_tpu.ops.layers import gelu


@dataclass(frozen=True)
class MoEConfig:
    d_model: int
    d_hidden: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coeff: float = 0.01
    dtype: Any = jnp.bfloat16


def moe_init(key: jax.Array, cfg: MoEConfig) -> dict:
    kr, k1, k2 = jax.random.split(key, 3)
    E, D, H = cfg.num_experts, cfg.d_model, cfg.d_hidden
    return {
        "router": jax.random.normal(kr, (D, E), jnp.float32) * 0.02,
        "w_in": jax.random.normal(k1, (E, D, H), jnp.float32) * (D**-0.5),
        "w_out": jax.random.normal(k2, (E, H, D), jnp.float32) * (H**-0.5),
    }


def moe_logical_axes() -> dict:
    """Logical axis names per param (for ray_tpu.parallel.sharding rules:
    'expert' maps to the ep mesh axis)."""
    return {
        "router": (None, None),
        "w_in": ("expert", None, "mlp"),
        "w_out": ("expert", "mlp", None),
    }


def moe_forward(params: dict, x: jax.Array, cfg: MoEConfig):
    """x: [tokens, d_model] -> (y, aux_loss).

    Dispatch/combine are dense one-hot einsums over a capacity-bounded
    buffer [E, C, D]; with w_in/w_out sharded on the expert axis XLA turns
    the [E, C, D] intermediates into all-to-alls across ep.
    """
    T, D = x.shape
    E, k = cfg.num_experts, cfg.top_k
    capacity = max(1, int(cfg.capacity_factor * k * T / E))

    router_logits = (x.astype(jnp.float32) @ params["router"])  # [T, E] f32
    probs = jax.nn.softmax(router_logits, axis=-1)

    # top-k expert choice per token
    gate_vals, expert_idx = jax.lax.top_k(probs, k)  # [T, k]
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    # position of each (token, choice) within its expert's capacity buffer
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)  # [T, k, E]
    flat = onehot.reshape(T * k, E)
    pos_in_expert = (jnp.cumsum(flat, axis=0) - flat).reshape(T, k, E)
    pos = jnp.sum(pos_in_expert * onehot, axis=-1)  # [T, k]
    keep = pos < capacity  # overflow tokens drop (standard Switch behavior)
    gate_vals = gate_vals * keep.astype(gate_vals.dtype)

    # dispatch tensor [T, k, E, C] — one-hot over (expert, slot)
    slot_onehot = jax.nn.one_hot(pos, capacity, dtype=cfg.dtype)  # [T, k, C]
    dispatch = (
        onehot.astype(cfg.dtype)[..., None] * slot_onehot[..., None, :]
    ) * keep.astype(cfg.dtype)[..., None, None]  # [T, k, E, C]
    combine = dispatch * gate_vals.astype(cfg.dtype)[..., None, None]

    xb = x.astype(cfg.dtype)
    expert_in = jnp.einsum("td,tkec->ecd", xb, dispatch)  # [E, C, D]
    h = gelu(jnp.einsum("ecd,edh->ech", expert_in, params["w_in"].astype(cfg.dtype)))
    expert_out = jnp.einsum("ech,ehd->ecd", h, params["w_out"].astype(cfg.dtype))
    y = jnp.einsum("ecd,tkec->td", expert_out, combine).astype(x.dtype)

    # load-balance aux loss (Switch eq. 4): E * sum_e f_e * P_e
    me = jnp.mean(probs, axis=0)  # mean router prob per expert
    # fraction of tokens whose top-1 choice is each expert
    ce = jnp.sum(
        jax.nn.one_hot(expert_idx[:, 0], E, dtype=jnp.float32), axis=0
    ) / T
    aux = cfg.aux_loss_coeff * E * jnp.sum(me * ce)
    return y, aux


def moe_reference_dense(params: dict, x: jax.Array, cfg: MoEConfig) -> jax.Array:
    """Every token through every chosen expert WITHOUT capacity limits —
    correctness oracle for tests (top-k gating, no drops)."""
    T, D = x.shape
    probs = jax.nn.softmax(x.astype(jnp.float32) @ params["router"], axis=-1)
    gate_vals, expert_idx = jax.lax.top_k(probs, cfg.top_k)
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)
    xb = x.astype(cfg.dtype)
    # [E, T, D]: run all tokens through all experts, then select
    h = gelu(jnp.einsum("td,edh->eth", xb, params["w_in"].astype(cfg.dtype)))
    all_out = jnp.einsum("eth,ehd->etd", h, params["w_out"].astype(cfg.dtype))
    out = jnp.zeros_like(xb)
    for j in range(cfg.top_k):
        sel = jnp.take_along_axis(
            all_out, expert_idx[None, :, j, None], axis=0
        )[0]  # [T, D]
        out = out + sel * gate_vals[:, j, None].astype(cfg.dtype)
    return out.astype(x.dtype)
