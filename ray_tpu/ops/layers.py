"""Elementwise / normalization / positional building blocks.

Pure-JAX ops that XLA fuses into surrounding matmuls (per the HBM-bandwidth
guidance: no hand-scheduling of what the compiler already fuses). Kept
dtype-disciplined: params may be f32 while activations run bf16; norms
accumulate in f32.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-6) -> jax.Array:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    out = x32 * jax.lax.rsqrt(var + eps)
    return (out * scale.astype(jnp.float32)).astype(dtype)


def layer_norm(
    x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float = 1e-5
) -> jax.Array:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (out * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


def gelu(x: jax.Array) -> jax.Array:
    return jax.nn.gelu(x, approximate=True)


def rope_cache(seq_len: int, head_dim: int, base: float = 10000.0):
    """(cos, sin) tables, f32, [seq, head_dim//2]."""
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def rope(x: jax.Array, cos: jax.Array, sin: jax.Array, positions=None) -> jax.Array:
    """Rotary embedding. x: [batch, seq, heads, head_dim]."""
    if positions is not None:
        cos = cos[positions]
        sin = sin[positions]
    # cos/sin: [seq, hd/2] -> broadcast over batch and heads
    while cos.ndim < x.ndim - 1:
        cos = cos[None]
        sin = sin[None]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    # [b, s, h, hd/2] * [1?, s, 1, hd/2]
    c = jnp.expand_dims(cos, -2)
    s = jnp.expand_dims(sin, -2)
    out = jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)
    return out.astype(x.dtype)
