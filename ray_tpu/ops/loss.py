"""Fused lm-head + cross-entropy: chunked over tokens, gradients computed
in the forward pass.

For a tied-embedding GPT, the vocabulary projection produces a [tokens,
vocab] f32 logits tensor that is larger than all transformer residuals
combined (GPT-2 small at bs16/seq1024: 3.3 GB, plus the same again for its
gradient under autodiff) — it is what blew the v5e's 15.75 GB HBM before
the last transformer matmul ever grew. But the loss gradient with respect
to logits is closed-form (softmax(logits) - onehot(target), scaled by the
upstream scalar), so the full tensor never needs to exist:

  scan over token chunks; per chunk compute logits -> lse/picked (the
  loss terms), form d_logits in closed form, and immediately contract it
  back down: dx_c = d_logits @ W  and  dW += d_logits^T @ x_c.

That is the SAME three matmuls the unfused forward+backward pair costs
(logits, dx, dW) — zero extra FLOPs — while peak memory drops from
O(tokens * vocab) to O(chunk * vocab), and the saved residuals are just
dx [tokens, d] and dW [vocab, d]. The custom VJP then only rescales by the
upstream cotangent. (Same design as GPU "fused linear cross-entropy"
kernels, e.g. Liger; the reference has no equivalent — its torch trainers
materialize logits.)

No reference counterpart (SURVEY.md §5.7 class: TPU-native compute ops).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_lm_head_loss(x, w, targets, mask, chunk=4096):
    """Mean next-token cross-entropy of `x @ w.T` against `targets`.

    x: [N, D] activations (any float dtype; matmuls run in x.dtype with
       f32 accumulation), w: [V, D] tied embedding table (cast to x.dtype),
    targets: [N] int32, mask: [N] float or None (1 = count this token).
    Returns a scalar f32 loss (mean over unmasked tokens).
    """
    loss, _ = _fused_fwd_impl(x, w, targets, mask, chunk)
    return loss


def _pad_to_chunks(x, targets, mask, chunk):
    n = x.shape[0]
    n_chunks = -(-n // chunk)
    pad = n_chunks * chunk - n
    if mask is None:
        mask = jnp.ones((n,), jnp.float32)
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        targets = jnp.pad(targets, (0, pad))
        mask = jnp.pad(mask, (0, pad))  # padded rows masked out
    return x, targets, mask, n_chunks, pad


def _fused_fwd_impl(x, w, targets, mask, chunk):
    n, d = x.shape
    v = w.shape[0]
    dtype = x.dtype
    wc = w.astype(dtype)
    xp, tp, mp, n_chunks, _ = _pad_to_chunks(x, targets, mask, chunk)
    xs = xp.reshape(n_chunks, chunk, d)
    ts = tp.reshape(n_chunks, chunk)
    ms = mp.reshape(n_chunks, chunk).astype(jnp.float32)

    def body(carry, sl):
        loss_sum, cnt, dw = carry
        xc, tc, mc = sl
        logits = jax.lax.dot_general(
            xc, wc, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                             # [C, V] f32
        lse = jax.scipy.special.logsumexp(logits, axis=-1)        # [C]
        picked = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
        loss_sum = loss_sum + jnp.sum((lse - picked) * mc)
        cnt = cnt + jnp.sum(mc)
        # closed-form d(sum CE)/d(logits), unnormalized: (p - onehot) * m
        p = jnp.exp(logits - lse[:, None])
        iota = jax.lax.broadcasted_iota(jnp.int32, (chunk, v), 1)
        dlogits = (p - (iota == tc[:, None]).astype(jnp.float32)) * mc[:, None]
        dxc = jax.lax.dot_general(
            dlogits.astype(dtype), wc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                             # [C, D]
        dw = dw + jax.lax.dot_general(
            dlogits.astype(dtype), xc, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                             # [V, D]
        return (loss_sum, cnt, dw), dxc

    init = (jnp.float32(0.0), jnp.float32(0.0), jnp.zeros((v, d), jnp.float32))
    if 0 < n_chunks <= 8:
        # unrolled chunk loop: XLA overlaps/schedules the per-chunk matmul
        # triplets across chunk boundaries instead of paying the scan-carry
        # tax (measured 36 -> 27 ms at 16k tokens on v5e — same reason the
        # GPT layer stack unrolls, see models/gpt.py scan_layers)
        carry, dx_list = init, []
        for i in range(n_chunks):
            carry, dxc = body(carry, (xs[i], ts[i], ms[i]))
            dx_list.append(dxc)
        (loss_sum, cnt, dw), dxs = carry, jnp.stack(dx_list)
    else:
        (loss_sum, cnt, dw), dxs = jax.lax.scan(body, init, (xs, ts, ms))
    cnt = jnp.maximum(cnt, 1.0)
    dx = dxs.reshape(n_chunks * chunk, d)[:n]
    return loss_sum / cnt, (dx / cnt, dw / cnt)


def _fused_fwd_rule(x, w, targets, mask, chunk):
    loss, (dx, dw) = _fused_fwd_impl(x, w, targets, mask, chunk)
    # residuals pre-cast to the primal dtypes (custom_vjp cotangent avals
    # must match the primals exactly)
    return loss, (dx.astype(x.dtype), dw.astype(w.dtype))


def _fused_bwd_rule(chunk, res, g):
    dx, dw = res
    gf = g.astype(jnp.float32)
    return (
        (gf * dx.astype(jnp.float32)).astype(dx.dtype),
        (gf * dw.astype(jnp.float32)).astype(dw.dtype),
        None,
        None,
    )


fused_lm_head_loss.defvjp(_fused_fwd_rule, _fused_bwd_rule)
