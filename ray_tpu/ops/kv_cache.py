"""Paged KV-cache array primitives: block-granular write / gather / attend /
copy (COW for the prefix cache).

The serving-side counterpart of ops/attention.py. A paged cache stores one
layer's keys/values as fixed-size physical blocks

    k_layer, v_layer: [num_blocks, block_size, n_kv_head, head_dim]

and each sequence owns a BLOCK TABLE — logical position p of sequence b
lives at (block_tables[b, p // block_size], p % block_size). Block tables
are dense int32 arrays padded with block 0, which is reserved as a garbage
sink: every out-of-range or padding write is redirected there, so the
scatter/gather ops below are mask-free and shape-static (XLA-friendly — no
dynamic shapes, bounded compile cache). Host-side block accounting (the
allocator, free lists, reuse) lives in serve/llm/kv_cache.py; these
functions are pure array ops so the model decode paths (models/gpt.py,
models/llama.py) can use them without depending on the serve layer.

Attention here is the XLA formulation, the CPU default and reference
semantics: decode gathers blocks, masks and softmaxes; prefill does the
same below ``PREFILL_STREAM_MIN_T`` and switches to an online-softmax
scan over block slabs above it (the padded context never materializes at
long T). The block-parallel Pallas decode AND prefill kernels with the
same call signatures live in ops/paged_attention.py; model steps pick
between backends via the ``decode_attention`` / ``prefill_attention``
dispatchers' ``backend`` knob (threaded from
EngineConfig.attention_backend). GQA never materializes repeated KV heads
in any path: the queries regroup onto their shared KV head and the
einsums carry the group as a free axis.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ray_tpu.ops.quantization import QuantizedKV, quantize_kv

NEG_INF = -1e30


def physical_slots(
    positions: jax.Array, block_tables: jax.Array, block_size: int
) -> tuple[jax.Array, jax.Array]:
    """Logical positions -> (physical block id, slot within block).

    positions: [B] or [B, S] int32; block_tables: [B, NB] int32. Positions
    outside the table range are clamped onto block 0 by the caller's
    masking; here indices are clamped so gathers stay in bounds.
    """
    idx = positions // block_size
    slot = positions % block_size
    idx = jnp.clip(idx, 0, block_tables.shape[1] - 1)
    if positions.ndim == 1:
        blk = jnp.take_along_axis(block_tables, idx[:, None], axis=1)[:, 0]
    else:
        blk = jnp.take_along_axis(block_tables, idx, axis=1)
    return blk, slot


def write_kv(
    k_layer: jax.Array,
    v_layer: jax.Array,
    k: jax.Array,
    v: jax.Array,
    positions: jax.Array,
    block_tables: jax.Array,
    *,
    valid: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Scatter new keys/values into a layer's paged cache.

    k, v: [B, H_kv, hd] (decode: one token per sequence, positions [B]) or
    [B, S, H_kv, hd] (prefill: positions [B, S]). `valid` masks rows/tokens
    that are padding — their writes are redirected to the reserved garbage
    block 0, slot 0, keeping the scatter shape-static.

    A ``QuantizedKV`` pool quantizes the incoming values at exactly this
    scatter's granularity — one amax per (token, kv-head) row — and lands
    data and scale with the same (blk, slot) indices, so incremental
    decode appends never touch (or re-quantize) previously written slots.
    """
    block_size = k_layer.shape[1]
    blk, slot = physical_slots(positions, block_tables, block_size)
    if valid is not None:
        blk = jnp.where(valid, blk, 0)
        slot = jnp.where(valid, slot, 0)
    if isinstance(k_layer, QuantizedKV):
        kind = "int8" if k_layer.data.dtype == jnp.int8 else "fp8"
        kq, ks = quantize_kv(k, kind)
        vq, vs = quantize_kv(v, kind)
        k_layer = QuantizedKV(
            k_layer.data.at[blk, slot].set(kq),
            k_layer.scale.at[blk, slot].set(ks),
        )
        v_layer = QuantizedKV(
            v_layer.data.at[blk, slot].set(vq),
            v_layer.scale.at[blk, slot].set(vs),
        )
        return k_layer, v_layer
    k_layer = k_layer.at[blk, slot].set(k.astype(k_layer.dtype))
    v_layer = v_layer.at[blk, slot].set(v.astype(v_layer.dtype))
    return k_layer, v_layer


def gather_kv(
    k_layer: jax.Array, v_layer: jax.Array, block_tables: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Materialize each sequence's cached context in position order:
    [B, NB * block_size, H_kv, hd]. Unallocated table entries point at the
    garbage block; the caller masks those positions.

    For a ``QuantizedKV`` pool this is the sanctioned XLA-fallback dequant
    (f32 out): the gathered context is ONE sequence batch's working set,
    never the whole pool — the full-pool-dequant lint in
    tests/test_sanitizers.py allowlists exactly this function and the
    streaming slab path below."""
    B, NB = block_tables.shape
    _, Bs, H, hd = k_layer.shape
    if isinstance(k_layer, QuantizedKV):
        keys = (
            k_layer.data[block_tables].astype(jnp.float32)
            * k_layer.scale[block_tables][..., None]
        ).reshape(B, NB * Bs, H, hd)
        values = (
            v_layer.data[block_tables].astype(jnp.float32)
            * v_layer.scale[block_tables][..., None]
        ).reshape(B, NB * Bs, H, hd)
        return keys, values
    keys = k_layer[block_tables].reshape(B, NB * Bs, H, hd)
    values = v_layer[block_tables].reshape(B, NB * Bs, H, hd)
    return keys, values


# Context length (NB * block_size) at and above which
# ``paged_prefill_attention`` switches from the dense one-shot formulation
# to the streaming (block-slab scan) one. The dense path keeps the full
# [B, S, Hkv, G, T] f32 score tensor live through softmax, an O(S*T) HBM
# spike that at the long contexts ROADMAP item 1 targets dwarfs the output;
# the streaming path peaks at one [B, S, Hkv, G, block_size] slab instead.
# Numerics differ at the last ulp (online vs one-shot softmax), so short
# contexts — everything the byte-identity tier-1 suite pins — keep the
# dense path bit-for-bit; tests monkeypatch this down to cover streaming.
PREFILL_STREAM_MIN_T = 2048


def _paged_prefill_streaming(
    qg: jax.Array,          # [B, S, Hkv, G, hd] regrouped queries
    k_layer: jax.Array,
    v_layer: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
    *,
    scale: float,
    window: int | None,
) -> jax.Array:
    """Online-softmax scan over physical block slabs: gathers ONE
    [B, block_size, Hkv, hd] slab per step instead of the whole padded
    context, carrying flash-style running (max, sum, acc). The padded
    [B, T] context and the [.., T] score tensor never exist in HBM."""
    B, S, Hkv, G, hd = qg.shape
    bs = k_layer.shape[1]
    NB = block_tables.shape[1]

    def _slab(carry, xs):
        m, l, acc = carry
        i, blk = xs
        if isinstance(k_layer, QuantizedKV):
            # per-slab dequant (one block's worth, in registers/VMEM —
            # never the whole pool); allowlisted by the dequant lint.
            kb, vb = k_layer[blk], v_layer[blk]
            keys = kb.data.astype(jnp.float32) * kb.scale[..., None]
            values = vb.data.astype(jnp.float32) * vb.scale[..., None]
        else:
            keys = k_layer[blk]      # [B, bs, Hkv, hd]
            values = v_layer[blk]
        s = jnp.einsum(
            "bshgd,bthd->bshgt", qg, keys,
            preferred_element_type=jnp.float32,
        ) * scale
        t = i * bs + jnp.arange(bs, dtype=positions.dtype)
        mask = t[None, None, :] <= positions[:, :, None]   # [B, S, bs]
        if window is not None:
            mask = jnp.logical_and(
                mask, t[None, None, :] > positions[:, :, None] - window
            )
        mask = mask[:, :, None, None, :]
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # p is explicitly zeroed where masked: for a fully-masked slab
        # m_new stays NEG_INF and exp(NEG_INF - NEG_INF) would be 1.
        p = jnp.where(mask, jnp.exp(s - m_new[..., None]), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bshgt,bthd->bshgd", p.astype(values.dtype), values,
            preferred_element_type=jnp.float32,
        )
        return (m_new, l_new, acc_new), None

    init = (
        jnp.full((B, S, Hkv, G), NEG_INF, jnp.float32),
        jnp.zeros((B, S, Hkv, G), jnp.float32),
        jnp.zeros((B, S, Hkv, G, hd), jnp.float32),
    )
    xs = (jnp.arange(NB, dtype=positions.dtype), block_tables.T)
    (_, l, acc), _ = jax.lax.scan(_slab, init, xs)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return acc / l_safe[..., None]


def paged_prefill_attention(
    q: jax.Array,
    k_layer: jax.Array,
    v_layer: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
    *,
    scale: float | None = None,
    window: int | None = None,
) -> jax.Array:
    """Multi-token (chunked-prefill) attention over a paged cache.

    q: [B, S, H_q, hd] — a CHUNK of queries whose K/V were already written
    via ``write_kv`` (so each query's own position is in the cache), with
    ``positions`` [B, S] giving every query's TRUE logical position. Each
    query attends over the sequence's full gathered context with the mask
    ``t <= position`` — i.e. all previously-cached tokens (an earlier
    chunk, or blocks mapped from a prefix cache) plus the causal part of
    its own chunk. ``window=W`` additionally masks ``t <= position - W``
    (sliding-window attention). Padding queries attend at whatever clamped
    position the caller gave them; their outputs are garbage the caller
    discards. Returns [B, S, H_q, hd] in q.dtype; GQA as in
    ``paged_attention``.

    Contexts at/above ``PREFILL_STREAM_MIN_T`` take the streaming path
    (``_paged_prefill_streaming``): the padded [B, T] gather and the full
    score tensor are never materialized — memory peaks at one block slab.
    """
    B, S, Hq, hd = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(hd)
    Hkv = k_layer.shape[2]
    # GQA without materializing rep x copies of K/V: queries regroup onto
    # their shared KV head ([B,S,Hq,hd] -> [B,S,Hkv,G,hd] — query head h
    # serves kv head h // G) and the einsums contract against the COMPACT
    # keys/values, carrying the group as a free axis.
    qg = q.reshape(B, S, Hkv, Hq // Hkv, hd)
    T = block_tables.shape[1] * k_layer.shape[1]
    if T >= PREFILL_STREAM_MIN_T:
        out = _paged_prefill_streaming(
            qg, k_layer, v_layer, block_tables, positions,
            scale=scale, window=window,
        )
        return out.reshape(B, S, Hq, hd).astype(q.dtype)
    keys, values = gather_kv(k_layer, v_layer, block_tables)  # [B,T,Hkv,hd]
    logits = jnp.einsum(
        "bshgd,bthd->bshgt", qg, keys, preferred_element_type=jnp.float32
    ) * scale
    mask = (
        jnp.arange(T, dtype=positions.dtype)[None, None, :]
        <= positions[:, :, None]
    )  # [B, S, T]
    if window is not None:
        mask = jnp.logical_and(
            mask,
            jnp.arange(T, dtype=positions.dtype)[None, None, :]
            > positions[:, :, None] - window,
        )
    logits = jnp.where(mask[:, :, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(values.dtype)
    out = jnp.einsum("bshgt,bthd->bshgd", probs, values)
    return out.reshape(B, S, Hq, hd).astype(q.dtype)


def _copy_blocks(
    cache_k: jax.Array, cache_v: jax.Array, src: jax.Array, dst: jax.Array
) -> tuple[jax.Array, jax.Array]:
    # cache_k/v: [n_layer, num_blocks, block_size, H_kv, hd] (plain pools)
    # or QuantizedKV pytrees whose scale leaf drops the trailing hd axis;
    # src/dst: [P]. The tree map moves every leaf — quantized COW clones
    # data AND scale planes in the same fused op, no dequant round-trip.
    def _cp(a):
        return a.at[:, dst].set(a[:, src])

    return jax.tree.map(_cp, cache_k), jax.tree.map(_cp, cache_v)


def _land_blocks(
    cache_k: jax.Array,
    cache_v: jax.Array,
    blocks: jax.Array,
    k_new: jax.Array,
    v_new: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    # cache_k/v: [n_layer, num_blocks, block_size, H_kv, hd] pools (or
    # QuantizedKV pytrees); blocks: [P]; k_new/v_new: matching payloads
    # [n_layer, P, ...] per leaf. Quantized handoffs land the wire's
    # already-quantized data and scale planes verbatim — bit-exact with
    # the exporter's pool, which is what keeps disaggregated streams
    # byte-identical within a quantized config.
    def _land(a, n):
        return a.at[:, blocks].set(n.astype(a.dtype))

    return (
        jax.tree.map(_land, cache_k, k_new),
        jax.tree.map(_land, cache_v, v_new),
    )


# Disaggregated-handoff landing: scatter externally-produced KV blocks
# (fetched from the object store by a decode replica) into the paged pool
# across all layers in one fused op. Callers pad the block-id list to a
# pow2 bucket with id 0 (the garbage block) and zero payload rows, so the
# jitted shape set stays closed exactly like ``copy_blocks``.
land_blocks = jax.jit(_land_blocks)


# Copy-on-write block duplication for the prefix cache: when a sequence
# must append into a block it shares with other sequences (or that is
# registered in the prefix-cache hash map), the host allocator points the
# sequence at a fresh block and this op clones the shared content into it,
# across all layers in one fused gather+scatter. Callers pad the (src,
# dst) id lists to a small bucket with (0, 0) identity pairs — copying
# the garbage block onto itself is a no-op — so the jitted shape set
# stays closed. Jitted once at module level: every engine in the process
# shares the compiled programs (same discipline as decode.py's _jit_cache).
copy_blocks = jax.jit(_copy_blocks)


def paged_attention(
    q: jax.Array,
    k_layer: jax.Array,
    v_layer: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
    *,
    scale: float | None = None,
) -> jax.Array:
    """Single-token decode attention over a paged cache.

    q: [B, H_q, hd] (the current token's query, AFTER its own k/v were
    written, so the mask `t <= position` includes self-attention).
    Returns [B, H_q, hd] in q.dtype. GQA: H_q may be a multiple of the
    cache's H_kv; the query group attends against the compact KV heads
    (no repeat — grouped einsum).
    """
    B, Hq, hd = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(hd)
    keys, values = gather_kv(k_layer, v_layer, block_tables)  # [B, T, Hkv, hd]
    Hkv = keys.shape[2]
    # GQA via grouped einsum over the compact KV heads (see
    # paged_prefill_attention) — no rep x K/V expansion in HBM.
    q = q.reshape(B, Hkv, Hq // Hkv, hd)
    logits = jnp.einsum(
        "bhgd,bthd->bhgt", q, keys, preferred_element_type=jnp.float32
    ) * scale
    T = keys.shape[1]
    mask = jnp.arange(T, dtype=positions.dtype)[None, :] <= positions[:, None]
    logits = jnp.where(mask[:, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(values.dtype)
    out = jnp.einsum("bhgt,bthd->bhgd", probs, values)
    return out.reshape(B, Hq, hd).astype(q.dtype)
