"""Attention ops: Pallas flash attention (TPU) + XLA reference.

New capability relative to the reference, which has no native attention or
sequence-parallel kernels at all (SURVEY.md §5.7 — long-context support in
the reference is delegated to DeepSpeed/FSDP integrations). Design per the
Pallas TPU guide, with three TPU-specific twists that fell out of profiling
on a v5e (these kernels are VPU- and grid-overhead-bound, not MXU-bound —
attention matmul FLOPs are ~1% of a GPT step but were ~40% of its time):

- GROUPED GRID: each grid step processes `group` (batch*head) slices at
  once via batched dot_generals, dividing the per-step overhead (~3-5 us
  of pipeline/DMA bookkeeping) by the group size. Grid is
  (bh/group, q_blocks, kv_blocks), innermost axis varies fastest so VMEM
  scratch accumulators persist across the reduction axis.
- BASE-2 SOFTMAX: log2(e) folds into the softmax scale (which itself folds
  into q once, O(S*D)), so the per-element transcendental is a bare exp2
  instead of exp's mul+exp2, and no [bq,bkv]-sized rescale pass exists.
- HALF-PRECISION EXP: when the inputs are bf16, the exp2/subtract run in
  bf16 (2x VPU lanes); the running max, log-sum-exp and output
  accumulation stay f32. Probabilities are bf16-quantized (~0.4% rel)
  — the same precision the output is stored at anyway. f32 inputs get a
  fully-f32 softmax (tests compare against the XLA reference at 1e-5).

Backward is a two-pass Pallas flash backward (dk/dv pass with q innermost,
dq pass with kv innermost) that recomputes score blocks against the
forward-saved logsumexp — O(S) residuals and no O(S^2) HBM temps (the
XLA-recompute backward it replaced materialized four [b,h,S,S] f32 tensors
per layer, the v5e OOM + bandwidth bottleneck at bs16/seq1024). The causal
mask is only computed on diagonal-crossing blocks; blocks fully below the
diagonal skip the iota/select entirely and blocks above are not executed.

The kernel runs in interpret mode on CPU (tests) and compiled on TPU.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30
LOG2E = math.log2(math.e)
LN2 = math.log(2.0)


def _tpu_compiler_params(pltpu, **kwargs):
    """Build TPU compiler params across jax versions: the class was named
    ``TPUCompilerParams`` before being renamed ``CompilerParams``."""
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


def mha_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Plain XLA attention. q,k,v: [B, H, S, D] (kv may have fewer heads =
    grouped-query; heads must divide)."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    q_heads, kv_heads = q.shape[1], k.shape[1]
    if q_heads != kv_heads:
        rep = q_heads // kv_heads
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        s_q, s_k = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _pick_group(bh: int, block_q: int, block_kv: int) -> int:
    """Largest group size whose f32 score temps stay well inside VMEM
    (~48 MB for ~3 [g,bq,bkv] f32 live values) and that divides bh."""
    budget = 48 * 1024 * 1024
    per = block_q * block_kv * 4 * 3
    g = min(max(1, budget // per), 8)  # cap BEFORE the divisibility walk
    while g > 1 and bh % g:
        g -= 1
    return g



def _clamp_block(block: int, seq_len: int) -> int:
    """Largest block <= `block` that divides seq_len (halving as needed, so
    e.g. S=1536 with a 1024 default lands on 512 instead of erroring)."""
    block = min(block, seq_len)
    while block > 1 and seq_len % block:
        block //= 2
    return block


def _causal_regimes(q_idx, kv_idx, block_q, block_kv):
    """(executed, fully_below): block-level causal classification."""
    executed = kv_idx * block_kv <= q_idx * block_q + (block_q - 1)
    fully_below = kv_idx * block_kv + (block_kv - 1) <= q_idx * block_q
    return executed, fully_below


def _mask_scores(s, q_idx, kv_idx, block_q, block_kv):
    g, bq, bkv = s.shape
    q_pos = q_idx * block_q + jax.lax.broadcasted_iota(jnp.int32, (g, bq, bkv), 1)
    k_pos = kv_idx * block_kv + jax.lax.broadcasted_iota(jnp.int32, (g, bq, bkv), 2)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _bdot(a, b, contract, batch=((0,), (0,)), out=jnp.float32):
    """Batched dot over leading group axis: a [g,M,*], b [g,N,*]."""
    return jax.lax.dot_general(
        a, b, ((contract), (batch)), preferred_element_type=out
    )


# ----------------------------------------------------------------------------
# Pallas forward kernel
# ----------------------------------------------------------------------------


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref,  # [g, block_q, D], [g, block_kv, D], [g, block_kv, D]
    o_ref,                # [g, block_q, D]
    *rest,                # optional lse_ref [g, block_q, 128], then scratch
    causal: bool,
    block_q: int,
    block_kv: int,
    save_lse: bool,
):
    from jax.experimental import pallas as pl

    if save_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        lse_ref, (m_scr, l_scr, acc_scr) = None, rest

    q_idx = pl.program_id(1)
    kv_idx = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(kv_idx == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute(masked: bool):
        # storage-dtype matmul operands: bf16 x bf16 -> f32 runs the MXU at
        # full rate. q arrives pre-scaled by softmax_scale * log2(e), so
        # the softmax is base-2 and needs no per-element rescale.
        q = q_ref[:]                               # [g, bq, D]
        k = k_ref[:]                               # [g, bkv, D]
        v = v_ref[:]                               # [g, bkv, D]
        s = _bdot(q, k, ((2,), (2,)))              # [g, bq, bkv] f32
        if masked:
            s = _mask_scores(s, q_idx, kv_idx, block_q, block_kv)

        m_prev = m_scr[:, :, :1]                   # [g, bq, 1]
        m_cur = jnp.max(s, axis=2, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # bf16 inputs: run the exp2 at half precision (2x VPU throughput);
        # the probabilities feed a bf16 matmul + an f32 row sum either way
        if q.dtype == jnp.bfloat16:
            p = jnp.exp2((s - m_new).astype(jnp.bfloat16))
        else:
            p = jnp.exp2(s - m_new)
        alpha = jnp.exp2(m_prev - m_new)           # [g, bq, 1]
        l_new = alpha * l_scr[:, :, :1] + jnp.sum(
            p, axis=2, keepdims=True, dtype=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + _bdot(
            p.astype(v.dtype), v, ((2,), (1,))
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        executed, fully_below = _causal_regimes(q_idx, kv_idx, block_q, block_kv)

        @pl.when(executed & jnp.logical_not(fully_below))
        def _():
            _compute(masked=True)

        @pl.when(fully_below)
        def _():
            _compute(masked=False)
    else:
        _compute(masked=False)

    @pl.when(kv_idx == n_kv - 1)
    def _finalize():
        l = l_scr[:, :, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[:] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        if save_lse:
            # base-2 logsumexp per query row, lane-broadcast to the
            # (8,128)-tiled output layout (m/l already hold 128 copies)
            lse_ref[:] = m_scr[:] + jnp.log2(
                jnp.where(l_scr[:] == 0.0, 1.0, l_scr[:])
            )


def _flash_forward(
    q, k, v, *, causal, scale, block_q, block_kv, interpret, save_lse=False
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, heads, seq_len, head_dim = q.shape
    block_q = _clamp_block(block_q, seq_len)
    block_kv = _clamp_block(block_kv, seq_len)
    bh = batch * heads
    g = _pick_group(bh, block_q, block_kv)
    # fold softmax scale AND log2(e) into q once (O(S*D)) — the kernels
    # compute a base-2 softmax with no per-score rescale pass
    qf = (q * jnp.asarray(scale * LOG2E, q.dtype)).reshape(bh, seq_len, head_dim)
    kf = k.reshape(bh, seq_len, head_dim)
    vf = v.reshape(bh, seq_len, head_dim)

    grid = (bh // g, seq_len // block_q, seq_len // block_kv)
    kernel = functools.partial(
        _flash_fwd_kernel,
        causal=causal,
        block_q=block_q,
        block_kv=block_kv,
        save_lse=save_lse,
    )
    out_specs = [
        pl.BlockSpec((g, block_q, head_dim), lambda b, i, j: (b, i, 0)),
    ]
    out_shapes = [jax.ShapeDtypeStruct((bh, seq_len, head_dim), q.dtype)]
    if save_lse:
        # lane-broadcast [bh, S, 128] rather than [bh, S]: a 2D output
        # violates Mosaic's (8,128) output-tile constraint; 128 copies of
        # a f32 scalar per row is ~64 bytes/token of extra HBM — noise
        out_specs.append(
            pl.BlockSpec((g, block_q, 128), lambda b, i, j: (b, i, 0))
        )
        out_shapes.append(jax.ShapeDtypeStruct((bh, seq_len, 128), jnp.float32))
    result = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((g, block_q, head_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((g, block_kv, head_dim), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((g, block_kv, head_dim), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((g, block_q, 128), jnp.float32),
            pltpu.VMEM((g, block_q, 128), jnp.float32),
            pltpu.VMEM((g, block_q, head_dim), jnp.float32),
        ],
        compiler_params=_tpu_compiler_params(pltpu,
            vmem_limit_bytes=100 * 1024 * 1024,
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(qf, kf, vf)
    out, lse = (result[0], result[1]) if save_lse else (result[0], None)
    out = out.reshape(batch, heads, seq_len, head_dim)
    if save_lse:
        return out, lse.reshape(batch, heads, seq_len, 128)[..., 0]
    return out


# ----------------------------------------------------------------------------
# Pallas backward kernels (two-pass flash backward)
#
# Pass 1 (dk, dv): grid (bh/g, kv_blocks, q_blocks) — q innermost so the
# dk/dv accumulators live in VMEM scratch across q steps.
# Pass 2 (dq):     grid (bh/g, q_blocks, kv_blocks) — kv innermost, ditto.
# Both recompute the score block from (q, k) and renormalize with the
# base-2 lse saved by the forward; delta = sum(do*o, -1) is precomputed in
# XLA. Nothing O(S^2) ever touches HBM.
# ----------------------------------------------------------------------------


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,  # blocks, see specs
    dk_ref, dv_ref,                                   # [g, block_kv, D]
    *rest,  # fused mode: dq_ref [g, 1, block_q, D] f32; then scratch x2
    causal: bool,
    block_q: int,
    block_kv: int,
    fused_dq: bool = False,
):
    from jax.experimental import pallas as pl

    if fused_dq:
        dq_ref, dk_scr, dv_scr = rest
    else:
        dq_ref, (dk_scr, dv_scr) = None, rest

    kv_idx = pl.program_id(1)
    q_idx = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(q_idx == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute(masked: bool):
        q = q_ref[:]                                  # [g, bq, D] pre-scaled
        k = k_ref[:]                                  # [g, bkv, D]
        v = v_ref[:]                                  # [g, bkv, D]
        do = do_ref[:]                                # [g, bq, D]
        lse = lse_ref[:, :, :1]                       # [g, bq, 1] f32, base-2
        delta = delta_ref[:, :, :1]                   # [g, bq, 1] f32

        s = _bdot(q, k, ((2,), (2,)))                 # [g, bq, bkv] f32
        if masked:
            s = _mask_scores(s, q_idx, kv_idx, block_q, block_kv)
        if q.dtype == jnp.bfloat16:
            p = jnp.exp2((s - lse).astype(jnp.bfloat16))
        else:
            p = jnp.exp2(s - lse)                     # normalized probs
        # dv += p^T @ do
        dv_scr[:] = dv_scr[:] + _bdot(
            p.astype(do.dtype), do, ((1,), (1,))
        )
        # dp = do @ v^T ; ds = ln2 * p * (dp - delta): the softmax is
        # base-2 (p = exp2(s2 - lse2) with s2 = log2e-scaled logits), so
        # dL/ds2 carries a ln2 from d exp2. With q pre-scaled by
        # scale*log2e, dk = ds^T @ q_scaled is then exact, and dq needs
        # one scale*log2e rescale in the wrapper (ln2 * log2e = 1).
        dp = _bdot(do, v, ((2,), (2,)))
        ds = p.astype(jnp.float32) * (dp - delta) * LN2
        dk_scr[:] = dk_scr[:] + _bdot(
            ds.astype(q.dtype), q, ((1,), (1,))
        )
        if dq_ref is not None:
            # fused single-sweep: the score block and dp are already in
            # VMEM, so the dq contribution of THIS kv block costs one
            # extra matmul — eliminating the entire second recompute pass
            # (3 of 7 matmul sweeps + its exp2/mask/DMA traffic)
            dq_ref[:, 0] = _bdot(ds.astype(k.dtype), k, ((2,), (1,)))

    if causal:
        executed, fully_below = _causal_regimes(q_idx, kv_idx, block_q, block_kv)

        if dq_ref is not None:
            # skipped blocks must still define their dq partial slot
            @pl.when(jnp.logical_not(executed))
            def _zero_dq():
                dq_ref[:, 0] = jnp.zeros_like(dq_ref[:, 0])

        @pl.when(executed & jnp.logical_not(fully_below))
        def _():
            _compute(masked=True)

        @pl.when(fully_below)
        def _():
            _compute(masked=False)
    else:
        _compute(masked=False)

    @pl.when(q_idx == n_q - 1)
    def _finalize():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref,                                           # [g, block_q, D]
    dq_scr,                                           # VMEM [g, block_q, D] f32
    *,
    causal: bool,
    block_q: int,
    block_kv: int,
):
    from jax.experimental import pallas as pl

    q_idx = pl.program_id(1)
    kv_idx = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(kv_idx == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute(masked: bool):
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        do = do_ref[:]
        lse = lse_ref[:, :, :1]
        delta = delta_ref[:, :, :1]

        s = _bdot(q, k, ((2,), (2,)))
        if masked:
            s = _mask_scores(s, q_idx, kv_idx, block_q, block_kv)
        if q.dtype == jnp.bfloat16:
            p = jnp.exp2((s - lse).astype(jnp.bfloat16))
        else:
            p = jnp.exp2(s - lse)
        dp = _bdot(do, v, ((2,), (2,)))
        ds = p.astype(jnp.float32) * (dp - delta) * LN2  # see dkv kernel
        dq_scr[:] = dq_scr[:] + _bdot(
            ds.astype(k.dtype), k, ((2,), (1,))
        )

    if causal:
        executed, fully_below = _causal_regimes(q_idx, kv_idx, block_q, block_kv)

        @pl.when(executed & jnp.logical_not(fully_below))
        def _():
            _compute(masked=True)

        @pl.when(fully_below)
        def _():
            _compute(masked=False)
    else:
        _compute(masked=False)

    @pl.when(kv_idx == n_kv - 1)
    def _finalize():
        dq_ref[:] = dq_scr[:].astype(dq_ref.dtype)


def _flash_backward(
    q, k, v, out, lse, do, *, causal, scale, block_q, block_kv, interpret
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, heads, seq_len, head_dim = q.shape
    block_q = _clamp_block(block_q, seq_len)
    block_kv = _clamp_block(block_kv, seq_len)
    bh = batch * heads
    g = _pick_group(bh, block_q, block_kv)
    # kernels compute grads w.r.t. the pre-scaled q (matching the forward's
    # folded scale*log2e); the chain rule back to q multiplies dq by the
    # same factor. For k and v no correction is needed: d s2/dk carries the
    # scaled q itself, and the ln2 from d exp2 cancels the folded log2(e)
    # in the ds -> (dk, dq) contractions' normalization (worked out so the
    # returned grads match the natural-base reference exactly).
    scale2 = scale * LOG2E
    qf = (q * jnp.asarray(scale2, q.dtype)).reshape(bh, seq_len, head_dim)
    kf = k.reshape(bh, seq_len, head_dim)
    vf = v.reshape(bh, seq_len, head_dim)
    dof = do.reshape(bh, seq_len, head_dim)

    # delta_i = dO_i . O_i (row dot), lane-broadcast alongside lse to the
    # (8,128)-tiled layout the kernels read; O(S*D) traffic, transient
    delta = jnp.sum(
        dof.astype(jnp.float32)
        * out.reshape(bh, seq_len, head_dim).astype(jnp.float32),
        axis=-1, keepdims=True,
    )                                                   # [bh, S, 1]
    delta_b = jnp.broadcast_to(delta, (bh, seq_len, 128))
    lse_b = jnp.broadcast_to(
        lse.reshape(bh, seq_len, 1), (bh, seq_len, 128)
    ).astype(jnp.float32)

    # pass 1: dk, dv — kv blocks outer, q blocks inner (b, j, i) grid order
    dkv_specs = [
        pl.BlockSpec((g, block_q, head_dim), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((g, block_kv, head_dim), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((g, block_kv, head_dim), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((g, block_q, head_dim), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((g, block_q, 128), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((g, block_q, 128), lambda b, j, i: (b, i, 0)),
    ]
    n_kv = seq_len // block_kv
    # Fused single sweep when the kv-block count is small: the dk/dv pass
    # already has the score block, dp, and k in VMEM, so each grid step
    # emits its dq partial (one extra matmul) into a per-kv-block slot and
    # XLA sums the n_kv slots — the entire dq recompute pass (3 of 7
    # matmul sweeps + its exp2/mask/DMA) disappears. Partials cost
    # bh*n_kv*S*hd f32 of HBM, so long sequences fall back to two-pass.
    fused = n_kv <= 4
    dkv_out_specs = [
        pl.BlockSpec((g, block_kv, head_dim), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((g, block_kv, head_dim), lambda b, j, i: (b, j, 0)),
    ]
    dkv_out_shapes = [
        jax.ShapeDtypeStruct((bh, seq_len, head_dim), k.dtype),
        jax.ShapeDtypeStruct((bh, seq_len, head_dim), v.dtype),
    ]
    if fused:
        dkv_out_specs.append(pl.BlockSpec(
            (g, 1, block_q, head_dim), lambda b, j, i: (b, j, i, 0)
        ))
        dkv_out_shapes.append(jax.ShapeDtypeStruct(
            (bh, n_kv, seq_len, head_dim), jnp.float32
        ))
    result = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, causal=causal,
            block_q=block_q, block_kv=block_kv, fused_dq=fused,
        ),
        grid=(bh // g, n_kv, seq_len // block_q),
        in_specs=dkv_specs,
        out_specs=dkv_out_specs,
        out_shape=dkv_out_shapes,
        scratch_shapes=[
            pltpu.VMEM((g, block_kv, head_dim), jnp.float32),
            pltpu.VMEM((g, block_kv, head_dim), jnp.float32),
        ],
        compiler_params=_tpu_compiler_params(pltpu,
            vmem_limit_bytes=100 * 1024 * 1024,
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(qf, kf, vf, dof, lse_b, delta_b)

    shape = (batch, heads, seq_len, head_dim)
    if fused:
        dk, dv, dq_parts = result
        dq = jnp.sum(dq_parts, axis=1).astype(q.dtype)
        dq = (dq * jnp.asarray(scale2, dq.dtype)).reshape(shape)
        return dq, dk.reshape(shape), dv.reshape(shape)
    dk, dv = result

    # pass 2: dq — q blocks outer, kv inner
    row_specs = [
        pl.BlockSpec((g, block_q, head_dim), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((g, block_kv, head_dim), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((g, block_kv, head_dim), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((g, block_q, head_dim), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((g, block_q, 128), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((g, block_q, 128), lambda b, i, j: (b, i, 0)),
    ]
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, causal=causal,
            block_q=block_q, block_kv=block_kv,
        ),
        grid=(bh // g, seq_len // block_q, seq_len // block_kv),
        in_specs=row_specs,
        out_specs=pl.BlockSpec(
            (g, block_q, head_dim), lambda b, i, j: (b, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((bh, seq_len, head_dim), q.dtype),
        scratch_shapes=[pltpu.VMEM((g, block_q, head_dim), jnp.float32)],
        compiler_params=_tpu_compiler_params(pltpu,
            vmem_limit_bytes=100 * 1024 * 1024,
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(qf, kf, vf, dof, lse_b, delta_b)

    dq = (dq * jnp.asarray(scale2, dq.dtype)).reshape(shape)
    return dq, dk.reshape(shape), dv.reshape(shape)


# ----------------------------------------------------------------------------
# custom VJP: pallas forward, pallas two-pass backward
# ----------------------------------------------------------------------------


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash_attention(q, k, v, causal, scale, block_q, block_kv, interpret):
    return _flash_forward(
        q, k, v, causal=causal, scale=scale,
        block_q=block_q, block_kv=block_kv, interpret=interpret,
    )


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_kv, interpret):
    out, lse = _flash_forward(
        q, k, v, causal=causal, scale=scale,
        block_q=block_q, block_kv=block_kv, interpret=interpret,
        save_lse=True,
    )
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, scale, block_q, block_kv, interpret, res, do):
    q, k, v, out, lse = res
    return _flash_backward(
        q, k, v, out, lse, do, causal=causal, scale=scale,
        block_q=block_q, block_kv=block_kv, interpret=interpret,
    )


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 1024,
    block_kv: int = 1024,
    interpret: bool | None = None,
) -> jax.Array:
    """Flash attention. q,k,v: [B, H, S, D]; returns [B, H, S, D].

    Grouped-query attention is handled by repeating kv heads up front
    (cheap relative to attention itself; a head-aware kernel is a later
    optimization). `interpret` defaults to True off-TPU so tests run the
    same kernel code on CPU. Default 1024 blocks: these kernels are
    grid-overhead-bound, so fewer/bigger blocks win on TPU (measured on
    v5e); long sequences clamp to the VMEM-driven group sizing.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform not in ("tpu", "axon")
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if q.shape[1] != k.shape[1]:
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    return _flash_attention(q, k, v, causal, scale, block_q, block_kv, interpret)
