"""Attention ops: Pallas flash attention (TPU) + XLA reference.

New capability relative to the reference, which has no native attention or
sequence-parallel kernels at all (SURVEY.md §5.7 — long-context support in
the reference is delegated to DeepSpeed/FSDP integrations). Design per the
Pallas TPU guide: online-softmax forward kernel, grid (batch*heads, q_blocks,
kv_blocks) with the kv axis innermost so VMEM scratch accumulators persist
across kv steps; backward is flash-recompute via XLA (per-q-block
re-materialization under `jax.checkpoint`-style recompute — keeps O(S)
memory for the residuals while XLA fuses the recomputed score matmuls).

The kernel runs in interpret mode on CPU (tests) and compiled on TPU.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def mha_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Plain XLA attention. q,k,v: [B, H, S, D] (kv may have fewer heads =
    grouped-query; heads must divide)."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    q_heads, kv_heads = q.shape[1], k.shape[1]
    if q_heads != kv_heads:
        rep = q_heads // kv_heads
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        s_q, s_k = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# ----------------------------------------------------------------------------
# Pallas forward kernel
# ----------------------------------------------------------------------------


def _flash_fwd_kernel(
    q_ref, k_ref, v_ref,  # [1, block_q, D], [1, block_kv, D], [1, block_kv, D]
    o_ref,                # [1, block_q, D]
    m_scr, l_scr, acc_scr,  # VMEM scratch: [bq,128], [bq,128], [bq,D]
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_kv: int,
    seq_len: int,
):
    from jax.experimental import pallas as pl

    q_idx = pl.program_id(1)
    kv_idx = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(kv_idx == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0].astype(jnp.float32)          # [bq, D]
        k = k_ref[0].astype(jnp.float32)          # [bkv, D]
        v = v_ref[0].astype(jnp.float32)          # [bkv, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                  # [bq, bkv]
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0
            )
            k_pos = kv_idx * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)

        m_prev = m_scr[:, :1]                      # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                     # [bq, bkv]
        alpha = jnp.exp(m_prev - m_new)            # [bq, 1]
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # skip fully-masked kv blocks above the diagonal
        @pl.when(kv_idx * block_kv <= q_idx * block_q + (block_q - 1))
        def _():
            _compute()
    else:
        _compute()

    @pl.when(kv_idx == n_kv - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _flash_forward(
    q, k, v, *, causal, scale, block_q, block_kv, interpret
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, heads, seq_len, head_dim = q.shape
    block_q = min(block_q, seq_len)
    block_kv = min(block_kv, seq_len)
    if seq_len % block_q or seq_len % block_kv:
        raise ValueError(
            f"seq_len {seq_len} must be divisible by block sizes "
            f"({block_q}, {block_kv})"
        )
    bh = batch * heads
    qf = q.reshape(bh, seq_len, head_dim)
    kf = k.reshape(bh, seq_len, head_dim)
    vf = v.reshape(bh, seq_len, head_dim)

    grid = (bh, seq_len // block_q, seq_len // block_kv)
    kernel = functools.partial(
        _flash_fwd_kernel,
        causal=causal,
        scale=scale,
        block_q=block_q,
        block_kv=block_kv,
        seq_len=seq_len,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_kv, head_dim), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_kv, head_dim), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, head_dim), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq_len, head_dim), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(batch, heads, seq_len, head_dim)


# ----------------------------------------------------------------------------
# custom VJP: pallas forward, XLA flash-recompute backward
# ----------------------------------------------------------------------------


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash_attention(q, k, v, causal, scale, block_q, block_kv, interpret):
    return _flash_forward(
        q, k, v, causal=causal, scale=scale,
        block_q=block_q, block_kv=block_kv, interpret=interpret,
    )


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_kv, interpret):
    out = _flash_forward(
        q, k, v, causal=causal, scale=scale,
        block_q=block_q, block_kv=block_kv, interpret=interpret,
    )
    return out, (q, k, v, out)


def _flash_bwd_rule(causal, scale, block_q, block_kv, interpret, res, do):
    q, k, v, out = res
    # Flash backward via recompute, in f32. XLA fuses the score recompute
    # with the gradient matmuls; memory is O(S^2) per (batch, head) shard
    # here — acceptable at the block sizes the Train layer uses, and the
    # ring-attention path (ops/ring_attention.py) keeps per-device S small.
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    outf = out.astype(jnp.float32)

    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf, preferred_element_type=jnp.float32) * scale
    if causal:
        s_q, s_k = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        s = jnp.where(mask, s, NEG_INF)
    # lse recomputed here rather than saved by the forward kernel: a 2D lse
    # output violates Mosaic's (8,128) output-tile constraint, and the
    # logsumexp falls out of the score recompute for free
    lse = jax.scipy.special.logsumexp(s, axis=-1, keepdims=True)
    p = jnp.exp(s - lse)                                # [b,h,q,k]
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vf)
    delta = jnp.sum(dof * outf, axis=-1, keepdims=True)  # [b,h,q,1]
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 512,
    block_kv: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """Flash attention. q,k,v: [B, H, S, D]; returns [B, H, S, D].

    Grouped-query attention is handled by repeating kv heads up front
    (cheap relative to attention itself; a head-aware kernel is a later
    optimization). `interpret` defaults to True off-TPU so tests run the
    same kernel code on CPU.
    """
    if interpret is None:
        interpret = jax.devices()[0].platform not in ("tpu", "axon")
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if q.shape[1] != k.shape[1]:
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    return _flash_attention(q, k, v, causal, scale, block_q, block_kv, interpret)
