"""Fused on-device token sampling for the serve/llm decode pipeline.

``sample_tokens`` turns a batch of next-token logits into sampled token
ids INSIDE the jitted model step (models/gpt.py, models/llama.py call it
when the engine passes a ``sample`` pytree), so the per-token
device->host transfer shrinks from O(batch x vocab) float32 logits to
O(batch) int32 ids and the host never touches a probability.

Determinism contract (the engine's failover story depends on it): the
per-token randomness is *stateless per (seed, position)* —

    key = fold_in(PRNGKey(request_seed), absolute_position_of_new_token)

so the token at position p is a pure function of (logits, seed, p). A
mid-stream resume that re-prefills ``prompt + delivered`` reproduces the
remaining tokens byte-identically by construction; no RNG state needs
fast-forwarding (this replaces the old host-side "burn one numpy uniform
per token" contract).

Kernel shape (TPU-friendly, no data-dependent shapes): the non-greedy
path sorts each row once with ``jax.lax.top_k(scaled, V)`` — a full
descending sort — then applies top-k as a rank mask, top-p as an
exclusive-cumsum mask over the sorted probabilities, and draws via
inverse CDF on the renormalized sorted distribution. Greedy rows
(temperature <= 0 or top_k == 1) are argmax; when the WHOLE batch is
greedy a ``lax.cond`` skips the sort entirely (the common serving
config), keeping the fused step as cheap as the old logits-returning one.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def apply_allow_mask(logits: jax.Array, mask: jax.Array | None) -> jax.Array:
    """Apply a packed uint32 allow-bitmask to ``logits`` [..., V].

    ``mask`` is ``[..., ceil(V/32)]`` uint32, little-endian packed (bit
    j of word w allows token ``w*32 + j``) — the grammar-constrained
    decoding mask staged by serve/llm/structured.py. Disallowed tokens
    go to ``-inf`` BEFORE the greedy argmax and the top-k sort, so the
    constrained token is still the same pure f(logits, seed, position)
    the failover-resume contract keys on; an all-ones mask is a bitwise
    identity, which is what keeps unconstrained rows byte-identical to
    a maskless build. Rows whose mask allows nothing are left unmasked
    (never NaN): the host-side FSM has already gone dead for such a row
    and terminates the stream, so its sampled token is never emitted.
    """
    if mask is None:
        return logits
    bits = (
        mask[..., None] >> jnp.arange(32, dtype=jnp.uint32)
    ) & jnp.uint32(1)
    allow = bits.reshape(mask.shape[:-1] + (mask.shape[-1] * 32,))
    allow = allow[..., : logits.shape[-1]] != 0
    any_allowed = jnp.any(allow, axis=-1, keepdims=True)
    allow = allow | ~any_allowed
    return jnp.where(allow, logits, -jnp.inf)


def _sampled_row(
    logits: jax.Array,
    seed: jax.Array,
    position: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
) -> jax.Array:
    """One row of the full temperature/top-k/top-p path. All inputs are
    scalars except ``logits`` [V]; returns a scalar int32 token id."""
    V = logits.shape[-1]
    key = jax.random.fold_in(jax.random.PRNGKey(seed), position)
    u = jax.random.uniform(key, dtype=jnp.float32)
    scaled = logits.astype(jnp.float32) / jnp.maximum(
        temperature, jnp.float32(1e-6)
    )
    # full descending sort: rank r holds the (r+1)-th largest logit
    srt, idx = jax.lax.top_k(scaled, V)
    ranks = jnp.arange(V, dtype=jnp.int32)
    k_eff = jnp.where(top_k > 0, top_k, V)
    srt = jnp.where(ranks < k_eff, srt, -jnp.inf)
    probs = jax.nn.softmax(srt)
    # top-p over the sorted distribution: keep ranks whose EXCLUSIVE
    # cumulative mass is below p (rank 0 always survives, so a tiny p
    # degrades to greedy rather than an empty support)
    p_eff = jnp.where((top_p > 0.0) & (top_p < 1.0), top_p, jnp.float32(1.0))
    keep = (jnp.cumsum(probs) - probs) < p_eff
    srt = jnp.where(keep, srt, -jnp.inf)
    probs = jax.nn.softmax(srt)
    pick = jnp.minimum(
        jnp.searchsorted(jnp.cumsum(probs), u, side="right"), V - 1
    )
    return idx[pick].astype(jnp.int32)


def sample_tokens(
    logits: jax.Array,
    positions: jax.Array,
    sample: dict,
) -> jax.Array:
    """Sample one token per row of ``logits`` [B, V] f32.

    ``positions`` [B] int32 is the ABSOLUTE sequence position of the token
    being sampled (prompt tokens occupy 0..len(prompt)-1, so the first
    generated token sits at len(prompt)). ``sample`` is a pytree of [B]
    arrays: ``seeds`` (uint32), ``temperature`` (f32, <= 0 -> greedy),
    ``top_k`` (int32, 0 -> full distribution), ``top_p`` (f32, >= 1 or
    <= 0 -> disabled), plus an optional ``mask`` ([B, ceil(V/32)]
    uint32 packed allow-bitmask; all-ones = unconstrained — see
    ``apply_allow_mask``). Returns [B] int32 token ids.
    """
    logits = apply_allow_mask(logits, sample.get("mask"))
    seeds = sample["seeds"]
    temperature = sample["temperature"]
    top_k = sample["top_k"]
    top_p = sample["top_p"]
    greedy_rows = (temperature <= 0.0) | (top_k == 1)
    # jnp.argmax matches np.argmax tie-breaking (first occurrence), which
    # is what the greedy-parity test pins down
    greedy_toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def all_greedy(_):
        return greedy_toks

    def mixed(_):
        sampled = jax.vmap(_sampled_row)(
            logits, seeds, positions, temperature, top_k, top_p
        )
        return jnp.where(greedy_rows, greedy_toks, sampled)

    return jax.lax.cond(jnp.all(greedy_rows), all_greedy, mixed, None)


def verify_tokens(
    logits: jax.Array,
    starts: jax.Array,
    draft_tokens: jax.Array,
    draft_len: jax.Array,
    sample: dict,
) -> jax.Array:
    """Speculative-decoding rejection epilogue over a [B, W] verify window.

    ``logits`` [B, W, V] f32 are the target model's outputs at window
    columns 0..W-1, where column 0 held the last COMMITTED token (absolute
    position ``starts`` [B]) and columns 1..W-1 held drafted candidates
    ``draft_tokens`` [B, W] (column 0 is the committed token itself;
    columns past ``draft_len`` [B] are padding). The logits at column s
    predict the token at absolute position starts + s + 1, so the target
    token for that position is the SAME pure function
    f(logits, seed, position) as non-speculative decode — ``sample_tokens``
    with keyed fold_in(seed, position) randomness.

    Acceptance is exact-match, not a probability-ratio test: draft column
    s is accepted iff it equals the target token the keyed sampler draws
    at that position given the (accepted, hence true) prefix. By induction
    the committed stream is byte-identical to non-speculative decoding —
    losslessness holds for greedy AND temperature/top-k/top-p, because the
    keyed sampler is deterministic per (logits, seed, position).

    Returns packed [B, W + 1] int32: column 0 = committed count c in
    1..draft_len+1 (accepted prefix plus one corrected/bonus token),
    columns 1..W = the target tokens for positions starts+1..starts+W —
    the committed tokens are packed[b, 1 : 1 + c]. One array => one
    device->host sync per verify step.
    """
    B, W, _ = logits.shape
    # target token for every window position, flattened through the [B, V]
    # sampler with per-row sample leaves tiled across the window
    positions = (
        starts[:, None] + 1 + jnp.arange(W, dtype=jnp.int32)[None, :]
    )  # [B, W]
    # per-row [B] leaves tile across the window; per-column leaves
    # ([B, W, ...] — the structured-decoding mask stages one allow-set
    # per window position) flatten row-major to match logits/positions
    tiled = {
        k: (
            v.reshape((B * W,) + v.shape[2:])
            if v.ndim >= 2
            else jnp.repeat(v, W, axis=0)
        )
        for k, v in sample.items()
    }
    tgt = sample_tokens(
        logits.reshape(B * W, -1), positions.reshape(B * W), tiled
    ).reshape(B, W)
    # leading run of draft columns matching the target drawn one column
    # earlier (logits at column s-1 predict position starts+s, which is
    # where draft column s sits)
    match = draft_tokens[:, 1:] == tgt[:, :-1]  # [B, W-1]
    within = (
        jnp.arange(1, W, dtype=jnp.int32)[None, :] <= draft_len[:, None]
    )
    accepted = jnp.sum(
        jnp.cumprod((match & within).astype(jnp.int32), axis=1), axis=1
    )  # [B] in 0..draft_len
    committed = accepted + 1  # + the corrected/bonus target token
    return jnp.concatenate(
        [committed[:, None].astype(jnp.int32), tgt], axis=1
    )
