"""Fused Pallas paged-attention decode kernel (block-table-aware, GQA-compact).

The TPU counterpart of ``ops/kv_cache.py::paged_attention`` for the
single-token decode hot loop. The XLA formulation gathers every sequence's
ENTIRE padded context (``gather_kv`` → ``[B, NB*block_size, H_kv, hd]``
in HBM) and repeats KV heads for GQA before a masked softmax — HBM traffic
inflated by the padding factor times the GQA repeat factor on an op that is
purely bandwidth-bound. This kernel is the vLLM PagedAttention shape
instead: one ``pallas_call`` whose grid walks each sequence's block table
and DMAs K/V **directly from the paged pool**
(``[num_blocks, block_size, n_kv_head, hd]``) block-by-block into VMEM.
Nothing is ever materialized at the padded context length, no head is ever
repeated.

Design (same playbook as ``ops/attention.py``'s flash kernels):

- BLOCK-TABLE WALK VIA SCALAR PREFETCH: the block table and positions ride
  in as ``PrefetchScalarGridSpec`` scalar operands, so the K/V BlockSpec
  index maps read ``tables[b, i]`` and point each grid step's DMA at the
  right physical block. Table entries wholly past a sequence's length
  re-issue the previous step's block index, which Pallas dedupes into NO
  DMA at all — padding costs neither bandwidth nor compute.
- GQA COMPACTION: queries reshape ``[B, H_q, hd] → [B, H_kv, G, hd]``
  (``G = H_q // H_kv``) and the grid iterates KV heads; each step computes
  the whole query group against the SHARED KV block with one batched dot,
  so GQA is a free extra row dimension instead of a ``rep``× KV copy.
- FLASH RUNNING SOFTMAX: per-(b, kv-head) running max / sum / accumulator
  live in VMEM scratch across the innermost block axis; the softmax is
  base-2 with ``scale * log2(e)`` folded into q once (exp2 instead of
  exp, no rescale pass), bf16 inputs run the exp2 at half precision.

Sharded executors (serve/llm/executor.py ``ShardedExecutor``) split the
pool's KV-head axis over tp. The kernel is head-count-agnostic — the grid
reads ``H_kv`` from the array it is handed, so each GSPMD shard runs the
identical program over its local heads (per-shard head count; an explicit
shard_map wrap is equivalent and not required). On CPU the kernel runs in
interpret mode (pure-XLA lowering, same policy as ``flash_attention``), so
tier-1 tests execute the real kernel code and GSPMD partitions it like any
other HLO.

``decode_attention`` is the dispatcher the model decode steps call: the
``backend`` knob ("auto" | "xla" | "pallas") threads down from
``EngineConfig.attention_backend`` via the model config, with "auto"
resolving to the Pallas kernel on TPU and the XLA formulation elsewhere
(CPU interpret-mode grids are trace-time-unrolled — correct, but not a
default worth paying for).

PREFILL (ISSUE 18): ``paged_prefill_attention_pallas`` is the multi-token
sibling — the same block-table walk extended with a query-block axis, so
fresh prefill, chunked prefill at true-position offsets, and the PR-8
verify windows all run off the paged pool without ever materializing the
padded ``[B, T]`` context. Grid ``(B, Hkv, q_blocks, kv_blocks)``; the
causal frontier per (b, q-block) rides in as scalar-prefetch operands
(``qmax``/``qmin``, reduced from the per-row positions), so kv-blocks
wholly past the frontier are skipped — compute AND (via index-map
dedupe) DMA — which is where the asymptotic win over the dense XLA path
comes from on long contexts: a chunk of C queries against a T-token
context costs O(C·T_attended) tiles instead of O(C·T_padded) HBM gather
traffic. A static ``window=`` arg adds the sliding-window variant that
also skips kv-blocks below the window floor. ``prefill_attention`` is
the dispatcher the model prefill/verify paths call, behind the same
``attention_backend`` knob as decode.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import LOG2E, NEG_INF
from ray_tpu.ops.quantization import QuantizedKV

BACKENDS = ("auto", "xla", "pallas")


def _tpu_compiler_params(**kwargs):
    """Build TPU compiler params across jax versions: the class was named
    ``TPUCompilerParams`` before being renamed ``CompilerParams``."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


def resolve_backend(backend: str) -> str:
    """Normalize the attention_backend knob to a concrete backend."""
    if backend == "auto":
        return (
            "pallas"
            if jax.devices()[0].platform in ("tpu", "axon")
            else "xla"
        )
    if backend not in ("xla", "pallas"):
        raise ValueError(
            f"attention_backend must be one of {BACKENDS}, got {backend!r}"
        )
    return backend


def _paged_decode_kernel(
    tables_ref,  # scalar prefetch: [B, NB] int32 block tables
    pos_ref,     # scalar prefetch: [B] int32 positions (mask is t <= pos)
    q_ref,       # [1, 1, G, hd] — this (b, kv-head)'s query group, pre-scaled
    k_ref,       # [1, bs, 1, hd] — one physical KV block, one kv head
    v_ref,       # [1, bs, 1, hd]
    *rest,       # quantized: (ks_ref, vs_ref, o_ref, scratch...) — the
                 # [1, bs, 1] per-(slot, head) f32 scale tiles ride the
                 # same block-table walk as their K/V tiles; else
                 # (o_ref, scratch...)
    block_size: int,
    quantized: bool,
):
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest

    b = pl.program_id(0)
    i = pl.program_id(2)
    n_nb = pl.num_programs(2)
    pos = pos_ref[b]

    @pl.when(i == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Blocks that start past the sequence's last valid position contribute
    # nothing; their (deduped) fetch is skipped and so is their compute.
    @pl.when(i * block_size <= pos)
    def _compute():
        if quantized:
            # in-register dequant: one [bs, hd] tile at a time, scaled by
            # its [bs] per-(slot, head) factors — the f32 K/V never exist
            # outside VMEM/registers.
            q = q_ref[0, 0].astype(jnp.float32)
            k = (
                k_ref[0, :, 0, :].astype(jnp.float32)
                * ks_ref[0, :, 0][:, None]
            )
            v = (
                v_ref[0, :, 0, :].astype(jnp.float32)
                * vs_ref[0, :, 0][:, None]
            )
        else:
            q = q_ref[0, 0]        # [G, hd], pre-scaled by scale * log2(e)
            k = k_ref[0, :, 0, :]  # [bs, hd]
            v = v_ref[0, :, 0, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                      # [G, bs]
        t = i * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(t <= pos, s, NEG_INF)

        m_prev = m_scr[:, :1]                       # [G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # bf16 inputs: exp2 at half precision (2x VPU lanes), matching the
        # flash forward; f32 inputs keep a fully-f32 softmax
        if q.dtype == jnp.bfloat16:
            p = jnp.exp2((s - m_new).astype(jnp.bfloat16))
        else:
            p = jnp.exp2(s - m_new)
        alpha = jnp.exp2(m_prev - m_new)
        l_new = alpha * l_scr[:, :1] + jnp.sum(
            p, axis=1, keepdims=True, dtype=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(i == n_nb - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def paged_attention_pallas(
    q: jax.Array,
    k_layer: jax.Array,
    v_layer: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
    *,
    scale: float | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Single-token decode attention straight off the paged KV pool.

    Same contract as ``ops/kv_cache.paged_attention``: q ``[B, H_q, hd]``
    (the current token's query, AFTER its own k/v were written, so the
    ``t <= position`` mask includes self), pool layers
    ``[num_blocks, block_size, H_kv, hd]``, ``block_tables`` ``[B, NB]``
    int32 padded with the garbage block 0, ``positions`` ``[B]`` int32.
    Returns ``[B, H_q, hd]`` in q.dtype. ``interpret`` defaults to True
    off-TPU so tests execute the kernel on CPU.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.devices()[0].platform not in ("tpu", "axon")
    quantized = isinstance(k_layer, QuantizedKV)
    if quantized:
        k_data, k_scale = k_layer.data, k_layer.scale
        v_data, v_scale = v_layer.data, v_layer.scale
    else:
        k_data, v_data = k_layer, v_layer
    B, Hq, hd = q.shape
    _, bs, Hkv, _ = k_data.shape
    if Hq % Hkv:
        raise ValueError(
            f"query heads ({Hq}) must be a multiple of KV heads ({Hkv})"
        )
    G = Hq // Hkv
    NB = block_tables.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(hd)
    # fold softmax scale AND log2(e) into q once — base-2 softmax in-kernel.
    # Query head h serves kv head h // G, so [B, Hq, hd] -> [B, Hkv, G, hd]
    # is exactly the jnp.repeat head mapping, compacted.
    qf = (q * jnp.asarray(scale * LOG2E, q.dtype)).reshape(B, Hkv, G, hd)
    tables = block_tables.astype(jnp.int32)
    pos = positions.astype(jnp.int32)

    def q_map(b, h, i, tables_ref, pos_ref):
        return (b, h, 0, 0)

    def kv_map(b, h, i, tables_ref, pos_ref):
        # Walk the sequence's block table. Entries wholly past the last
        # valid position re-issue entry 0's index: consecutive identical
        # block tuples make Pallas skip the DMA, so table padding costs
        # no bandwidth (the kernel skips their compute by the same test).
        entry = jnp.where(
            i * bs <= pos_ref[b], tables_ref[b, i], tables_ref[b, 0]
        )
        return (entry, 0, h, 0)

    def kv_scale_map(b, h, i, tables_ref, pos_ref):
        # Same walk as kv_map, minus the trailing head_dim coordinate —
        # a scale tile is fetched iff its K/V tile is.
        entry = jnp.where(
            i * bs <= pos_ref[b], tables_ref[b, i], tables_ref[b, 0]
        )
        return (entry, 0, h)

    in_specs = [
        pl.BlockSpec((1, 1, G, hd), q_map),
        pl.BlockSpec((1, bs, 1, hd), kv_map),
        pl.BlockSpec((1, bs, 1, hd), kv_map),
    ]
    operands = [tables, pos, qf, k_data, v_data]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, bs, 1), kv_scale_map),
            pl.BlockSpec((1, bs, 1), kv_scale_map),
        ]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, NB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, G, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((G, 128), jnp.float32),
            pltpu.VMEM((G, 128), jnp.float32),
            pltpu.VMEM((G, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_decode_kernel, block_size=bs, quantized=quantized
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, hd), q.dtype),
        compiler_params=_tpu_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024,
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    return out.reshape(B, Hq, hd)


def decode_attention(
    q: jax.Array,
    k_layer: jax.Array,
    v_layer: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
    *,
    scale: float | None = None,
    backend: str = "auto",
) -> jax.Array:
    """Backend dispatcher for decode attention — the one entry point the
    model decode steps call. ``backend`` is the ``attention_backend`` knob
    threaded from ``EngineConfig`` through the model config; "auto" picks
    the Pallas kernel on TPU and the XLA formulation elsewhere. Both
    backends share the exact call signature and numerics contract
    (token streams are byte-identical — tests/test_paged_attention.py)."""
    if resolve_backend(backend) == "pallas":
        return paged_attention_pallas(
            q, k_layer, v_layer, block_tables, positions, scale=scale
        )
    from ray_tpu.ops.kv_cache import paged_attention as _xla_paged_attention

    return _xla_paged_attention(
        q, k_layer, v_layer, block_tables, positions, scale=scale
    )


def _paged_prefill_kernel(
    tables_ref,   # scalar prefetch: [B, NB] int32 block tables
    qmax_ref,     # scalar prefetch: [B, nqb] int32 frontier per q-block
    qmin_ref,     # scalar prefetch: [B, nqb] int32 floor per q-block
    q_ref,        # [1, 1, qb*G, hd] — this (b, kv-head, q-block)'s rows,
                  # pre-scaled, row r = query (r // G) of the block, group
                  # member (r % G)
    pos_ref,      # [1, qb] int32 — true positions of this q-block's rows
    k_ref,        # [1, bs, 1, hd] — one physical KV block, one kv head
    v_ref,        # [1, bs, 1, hd]
    *rest,        # quantized: (ks_ref, vs_ref, o_ref, scratch...) — the
                  # [1, bs, 1] per-(slot, head) f32 scale tiles ride the
                  # same frontier-gated block-table walk as K/V; else
                  # (o_ref, scratch...)
    block_size: int,
    gqa: int,
    window: int | None,
    quantized: bool,
):
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest

    b = pl.program_id(0)
    j = pl.program_id(2)
    i = pl.program_id(3)
    n_kv = pl.num_programs(3)
    qmax = qmax_ref[b, j]

    @pl.when(i == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # kv-blocks entirely past this q-block's causal frontier contribute
    # nothing — their (deduped) fetch is skipped and so is their compute.
    # With a sliding window, blocks entirely below the window floor of the
    # EARLIEST query in the block are skipped the same way.
    needed = i * block_size <= qmax
    if window is not None:
        needed = jnp.logical_and(
            needed, (i + 1) * block_size > qmin_ref[b, j] - (window - 1)
        )

    @pl.when(needed)
    def _compute():
        if quantized:
            # in-register dequant, one [bs, hd] tile at a time (see
            # _paged_decode_kernel) — no f32 KV tensor in HBM.
            q = q_ref[0, 0].astype(jnp.float32)
            k = (
                k_ref[0, :, 0, :].astype(jnp.float32)
                * ks_ref[0, :, 0][:, None]
            )
            v = (
                v_ref[0, :, 0, :].astype(jnp.float32)
                * vs_ref[0, :, 0][:, None]
            )
        else:
            q = q_ref[0, 0]    # [qb*G, hd], pre-scaled by scale * log2(e)
            k = k_ref[0, :, 0, :]  # [bs, hd]
            v = v_ref[0, :, 0, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                      # [qb*G, bs]
        # per-ROW causal mask: row r carries query (r // G)'s true
        # position; expand the [qb] position tile across the G group
        # members (broadcast + reshape — never a head repeat in HBM)
        qb = pos_ref.shape[1]
        pos_rows = jnp.broadcast_to(
            pos_ref[0][:, None], (qb, gqa)
        ).reshape(qb * gqa, 1)
        t = i * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = t <= pos_rows
        if window is not None:
            mask = jnp.logical_and(mask, t > pos_rows - window)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, :1]                       # [qb*G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # bf16 inputs: exp2 at half precision (2x VPU lanes), matching the
        # flash forward; f32 inputs keep a fully-f32 softmax
        if q.dtype == jnp.bfloat16:
            p = jnp.exp2((s - m_new).astype(jnp.bfloat16))
        else:
            p = jnp.exp2(s - m_new)
        alpha = jnp.exp2(m_prev - m_new)
        l_new = alpha * l_scr[:, :1] + jnp.sum(
            p, axis=1, keepdims=True, dtype=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(i == n_kv - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def paged_prefill_attention_pallas(
    q: jax.Array,
    k_layer: jax.Array,
    v_layer: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
    *,
    scale: float | None = None,
    window: int | None = None,
    q_block: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Multi-token (prefill / chunked-prefill / verify-window) attention
    straight off the paged KV pool.

    Same contract as ``ops/kv_cache.paged_prefill_attention``: q
    ``[B, S, H_q, hd]`` is a CHUNK of queries whose own K/V were already
    written via ``write_kv``, ``positions`` ``[B, S]`` int32 gives every
    query's TRUE logical position (callers zero padding columns — their
    outputs are garbage the caller discards), pool layers
    ``[num_blocks, block_size, H_kv, hd]``, ``block_tables`` ``[B, NB]``
    int32 padded with the garbage block 0. Returns ``[B, S, H_q, hd]``
    in q.dtype.

    The grid is ``(B, H_kv, q_blocks, kv_blocks)`` with the kv axis
    innermost: per (b, kv-head, q-block) the flash running softmax walks
    the sequence's block table, DMAing one physical ``[block_size, hd]``
    tile per step. The per-(b, q-block) causal frontier (``max`` of the
    block's positions) and floor (``min``) ride in as scalar-prefetch
    operands next to the block table: the index map re-issues block 0's
    index for kv-blocks the q-block cannot attend (Pallas dedupes the
    DMA) and ``@pl.when`` skips their compute. ``window=W`` (static)
    additionally masks ``t <= pos - W`` and skips kv-blocks wholly below
    the window floor — sliding-window attention at O(S·W) cost.

    ``q_block`` tiles the chunk axis (default: whole chunk up to 128
    rows; S is padded up to a multiple with position-0 rows and the pad
    is sliced off). ``interpret`` defaults to True off-TPU so tier-1
    executes the kernel on CPU.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.devices()[0].platform not in ("tpu", "axon")
    quantized = isinstance(k_layer, QuantizedKV)
    if quantized:
        k_data, k_scale = k_layer.data, k_layer.scale
        v_data, v_scale = v_layer.data, v_layer.scale
    else:
        k_data, v_data = k_layer, v_layer
    B, S, Hq, hd = q.shape
    _, bs, Hkv, _ = k_data.shape
    if Hq % Hkv:
        raise ValueError(
            f"query heads ({Hq}) must be a multiple of KV heads ({Hkv})"
        )
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    G = Hq // Hkv
    NB = block_tables.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(hd)
    qb = q_block if q_block is not None else min(S, 128)
    nqb = -(-S // qb)
    Sp = nqb * qb
    pos = positions.astype(jnp.int32)
    if Sp != S:
        q = jnp.pad(q, ((0, 0), (0, Sp - S), (0, 0), (0, 0)))
        pos = jnp.pad(pos, ((0, 0), (0, Sp - S)))
    # fold softmax scale AND log2(e) into q once — base-2 softmax
    # in-kernel. [B, S, Hq, hd] -> [B, Hkv, S*G, hd]: query head h serves
    # kv head h // G (the jnp.repeat head mapping, compacted), and the
    # (query, group) rows flatten s-major so a q tile is G-contiguous.
    qf = (q * jnp.asarray(scale * LOG2E, q.dtype)).reshape(
        B, Sp, Hkv, G, hd
    ).transpose(0, 2, 1, 3, 4).reshape(B, Hkv, Sp * G, hd)
    tables = block_tables.astype(jnp.int32)
    posb = pos.reshape(B, nqb, qb)
    # causal frontier / window floor per (b, q-block) — the scalars the
    # index map and @pl.when guards read. Padding rows sit at position 0,
    # so they never extend the frontier (and only make the floor
    # conservative, never wrong).
    qmax = jnp.max(posb, axis=2).astype(jnp.int32)
    qmin = jnp.min(posb, axis=2).astype(jnp.int32)

    def q_map(b, h, j, i, tables_ref, qmax_ref, qmin_ref):
        return (b, h, j, 0)

    def pos_map(b, h, j, i, tables_ref, qmax_ref, qmin_ref):
        return (b, j)

    def kv_map(b, h, j, i, tables_ref, qmax_ref, qmin_ref):
        # Walk the sequence's block table. kv-blocks the q-block cannot
        # attend (wholly past its frontier, or — windowed — wholly below
        # its floor) re-issue entry 0's index: consecutive identical
        # block tuples make Pallas skip the DMA, so skipped blocks cost
        # no bandwidth (their compute is skipped by the same test).
        needed = i * bs <= qmax_ref[b, j]
        if window is not None:
            needed = jnp.logical_and(
                needed, (i + 1) * bs > qmin_ref[b, j] - (window - 1)
            )
        entry = jnp.where(needed, tables_ref[b, i], tables_ref[b, 0])
        return (entry, 0, h, 0)

    def kv_scale_map(b, h, j, i, tables_ref, qmax_ref, qmin_ref):
        # Same frontier-gated walk as kv_map, minus the trailing head_dim
        # coordinate — a scale tile is fetched iff its K/V tile is.
        needed = i * bs <= qmax_ref[b, j]
        if window is not None:
            needed = jnp.logical_and(
                needed, (i + 1) * bs > qmin_ref[b, j] - (window - 1)
            )
        entry = jnp.where(needed, tables_ref[b, i], tables_ref[b, 0])
        return (entry, 0, h)

    in_specs = [
        pl.BlockSpec((1, 1, qb * G, hd), q_map),
        pl.BlockSpec((1, qb), pos_map),
        pl.BlockSpec((1, bs, 1, hd), kv_map),
        pl.BlockSpec((1, bs, 1, hd), kv_map),
    ]
    operands = [tables, qmax, qmin, qf, pos, k_data, v_data]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, bs, 1), kv_scale_map),
            pl.BlockSpec((1, bs, 1), kv_scale_map),
        ]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, Hkv, nqb, NB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, qb * G, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((qb * G, 128), jnp.float32),
            pltpu.VMEM((qb * G, 128), jnp.float32),
            pltpu.VMEM((qb * G, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_prefill_kernel, block_size=bs, gqa=G, window=window,
            quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, Sp * G, hd), q.dtype),
        compiler_params=_tpu_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024,
            dimension_semantics=(
                "parallel", "parallel", "parallel", "arbitrary"
            ),
        ),
        interpret=interpret,
    )(*operands)
    out = out.reshape(B, Hkv, Sp, G, hd).transpose(0, 2, 1, 3, 4)
    return out.reshape(B, Sp, Hq, hd)[:, :S]


def prefill_attention(
    q: jax.Array,
    k_layer: jax.Array,
    v_layer: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
    *,
    scale: float | None = None,
    backend: str = "auto",
    window: int | None = None,
) -> jax.Array:
    """Backend dispatcher for multi-token paged attention — the one entry
    point the model prefill, chunked-prefill, and verify paths call.
    ``backend`` is the same ``attention_backend`` knob as
    ``decode_attention`` (static in the traced step, part of the engine's
    jit-cache key, zero new compile kinds); both backends share the exact
    call signature and numerics contract, so token streams are
    byte-identical across them (tests/test_paged_attention.py).
    ``window`` selects sliding-window attention (see
    ``paged_prefill_attention_pallas``)."""
    if resolve_backend(backend) == "pallas":
        return paged_prefill_attention_pallas(
            q, k_layer, v_layer, block_tables, positions,
            scale=scale, window=window,
        )
    from ray_tpu.ops.kv_cache import (
        paged_prefill_attention as _xla_paged_prefill,
    )

    return _xla_paged_prefill(
        q, k_layer, v_layer, block_tables, positions,
        scale=scale, window=window,
    )
