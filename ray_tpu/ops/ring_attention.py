"""Ring attention: sequence/context parallelism over an ICI ring axis.

New capability — the reference has none (SURVEY.md §5.7: no ring attention,
sequence or context parallelism anywhere; grep returns nothing). Design:
KV shards rotate around the `sp` mesh axis via `ppermute` while each device
holds its Q shard; per-step partial attention is combined with the online
softmax (running max/denominator), so the full S×S score matrix never
materializes on any one device — per-device memory is O(S_local²).

Used inside `shard_map` over the sequence axis (see
ray_tpu/parallel/sp.py for the train-layer entry point). The per-block
compute is XLA-level here; the Pallas flash kernel can replace the block
einsums once it returns (m, l) residuals — same combination algebra.
"""
from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Attention where K/V are sharded over `axis_name` and rotate.

    Must be called inside shard_map with q,k,v local shards [B,H,S_loc,D].
    Returns the local output shard [B,H,S_loc,D].
    """
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape

    qf = q.astype(jnp.float32)

    def step(j, carry):
        o_acc, m_acc, l_acc, k_rot, v_rot = carry
        # the kv block now held arrived from device (my_idx - j) mod n
        src = (my_idx - j) % n

        s = (
            jnp.einsum(
                "bhqd,bhkd->bhqk",
                qf,
                k_rot.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        if causal:
            q_pos = my_idx * s_loc + jax.lax.broadcasted_iota(
                jnp.int32, (s_loc, s_loc), 0
            )
            k_pos = src * s_loc + jax.lax.broadcasted_iota(
                jnp.int32, (s_loc, s_loc), 1
            )
            s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)

        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_acc, m_cur)
        # guard fully-masked rows (m_new == NEG_INF): exp(NEG_INF - NEG_INF)
        # would be 1; clamp the shift so those rows contribute 0
        shift = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(s - shift)
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        alpha = jnp.where(m_acc <= NEG_INF / 2, 0.0, jnp.exp(m_acc - shift))
        l_new = alpha * l_acc + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o_acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_rot.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = jax.lax.ppermute(k_rot, axis_name, perm)
        v_next = jax.lax.ppermute(v_rot, axis_name, perm)
        return o_new, m_new, l_new, k_next, v_next

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc, 1), jnp.float32)
    o, m, l, _, _ = jax.lax.fori_loop(0, n, step, (o0, m0, l0, k, v))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (o / l_safe).astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    batch_axes: tuple[str, ...] = ("dp", "fsdp"),
) -> jax.Array:
    """Global-view entry: q,k,v [B,H,S,D] with S sharded on `axis_name`.

    Wraps `ring_attention` in shard_map with batch sharded over the data
    axes and sequence over the ring axis.
    """
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map  # jax >= 0.8 (check_rep became check_vma)
        _rep_kw = {"check_vma": False}
    except ImportError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map
        _rep_kw = {"check_rep": False}

    spec = P(batch_axes, None, axis_name, None)
    fn = shard_map(
        partial(ring_attention, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **_rep_kw,
    )
    return fn(q, k, v)
