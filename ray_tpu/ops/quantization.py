"""Quantized serving primitives: int8 / fp8-e4m3 weights and paged-KV.

Two pytree container types carry (data, scale) pairs through every
existing seam without changing any call signature:

- ``QuantizedTensor`` — a weight. ``data`` holds the low-precision
  values, ``scale`` a broadcast-ready per-channel f32 factor (amax over
  the contraction axis, keepdims). Every serving-path weight use already
  spells ``params[name].astype(cfg.dtype)``; the ``astype`` method IS
  the dequant, so the model code is unchanged and XLA fuses the
  ``data * scale`` expansion into the consuming matmul/gather.
- ``QuantizedKV`` — one side (k or v) of the paged KV pool. ``data`` is
  the quantized pool array ``[..., block_size, n_kv_head, head_dim]``
  and ``scale`` the per-(token-write, kv-head) f32 plane
  ``data.shape[:-1]`` — scale granularity matches ``write_kv``'s
  scatter granularity exactly, so incremental decode appends never
  re-quantize a block and COW/land/demote move scale planes with their
  data through the same fused ops. Registered as a pytree: ``lax.scan``
  unstacks the layer axis of data and scale together, jit/device_put/
  tree.map all flow through, and leading-axis ``__getitem__`` keeps the
  host-side block plumbing (export / demote / wire stacking) generic.

Quantization GRANULARITY is per-channel / per-(token, head) — one amax
reduction, symmetric, no zero points: int8 uses s = amax/127 with
round-half-even, fp8-e4m3 uses s = amax/448 with the dtype's own cast
rounding. Both are bit-deterministic, which is what keeps chaos
failover / handoff / demote-promote / preempt-resume byte-identical
WITHIN a quantized config (the cross-config contract is the agreement
rate + perplexity gates in tests/test_serve_llm_quant.py, not byte
identity).

The full-pool dequant lint (tests/test_sanitizers.py) bans
``astype``-style dequantization of pool arrays outside the Pallas
kernels and the ``ops/kv_cache.py`` XLA fallback — dequant happens
per-tile in-register, never as an f32 KV tensor in HBM.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

# quantization kind -> (pool/weight dtype, symmetric max representable)
QUANT_KINDS = ("int8", "fp8")
_QMAX = {"int8": 127.0, "fp8": 448.0}  # e4m3fn saturates at +-448


def resolve_quantization(kind: Any) -> str | None:
    """Normalize the ``quantization`` knob: None/"" -> None (f32 serving),
    "int8" | "fp8" pass through. Anything else raises loudly — a typo'd
    config must never silently serve unquantized."""
    if kind is None or kind == "":
        return None
    if kind not in QUANT_KINDS:
        raise ValueError(
            f"quantization must be one of {QUANT_KINDS} or None, "
            f"got {kind!r}"
        )
    return kind


def quant_dtype(kind: str):
    """The storage dtype for a quantization kind (jnp dtype object)."""
    return jnp.int8 if kind == "int8" else jnp.float8_e4m3fn


def quant_max(kind: str) -> float:
    return _QMAX[kind]


@jax.tree_util.register_pytree_node_class
@dataclass
class QuantizedTensor:
    """A quantized weight: low-precision ``data`` + broadcast-ready
    per-channel f32 ``scale`` (same rank as data, size-1 on every axis
    except the channel axis). ``astype`` is the lazy dequant the model
    code already calls on every serving-path weight use."""

    data: Any
    scale: Any

    def tree_flatten(self):
        return (self.data, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    def astype(self, dtype):
        return self.data.astype(dtype) * self.scale.astype(dtype)


@jax.tree_util.register_pytree_node_class
@dataclass
class QuantizedKV:
    """One side of a quantized paged KV pool (or any host/device block
    slab cut from it): quantized ``data`` plus the f32 ``scale`` plane of
    shape ``data.shape[:-1]`` (one scale per written (token, kv-head) —
    the head_dim axis is the amax reduction). Leading-axis indexing
    slices both leaves, so ``cache.k[:, ids]`` / ``k[:, i]`` host
    plumbing works unchanged; leaves may be jax OR numpy arrays (the
    wire/demote paths carry numpy)."""

    data: Any
    scale: Any

    def tree_flatten(self):
        return (self.data, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def ndim(self):
        return self.data.ndim

    def __getitem__(self, idx):
        # valid for leading-axis indexing only (every host-side use):
        # the trailing head_dim axis exists on data but not on scale.
        return QuantizedKV(self.data[idx], self.scale[idx])


def quantize_kv(x: jax.Array, kind: str) -> tuple[jax.Array, jax.Array]:
    """Quantize fresh K or V values at write_kv granularity: amax over
    the trailing head_dim axis -> (data ``x.shape`` in the kind's dtype,
    scale ``x.shape[:-1]`` f32). Symmetric, deterministic (round-half-
    even for int8, the e4m3 cast's own rounding for fp8); an all-zero
    row quantizes to zeros under a unit scale."""
    qmax = quant_max(kind)
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.where(amax > 0.0, amax, 1.0) / qmax
    scaled = x.astype(jnp.float32) / scale[..., None]
    scaled = jnp.clip(scaled, -qmax, qmax)
    if kind == "int8":
        data = jnp.round(scaled).astype(jnp.int8)
    else:
        data = scaled.astype(jnp.float8_e4m3fn)
    return data, scale


def quantize_weight(w: jax.Array, axis: int, kind: str) -> QuantizedTensor:
    """Per-channel weight quantization: amax over the CONTRACTION axis
    (keepdims), so the scale attaches to output channels and
    ``astype``-dequant factorizes exactly through the consuming matmul."""
    qmax = quant_max(kind)
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.where(amax > 0.0, amax, 1.0) / qmax
    scaled = jnp.clip(w.astype(jnp.float32) / scale, -qmax, qmax)
    if kind == "int8":
        data = jnp.round(scaled).astype(jnp.int8)
    else:
        data = scaled.astype(jnp.float8_e4m3fn)
    return QuantizedTensor(data, scale)


def quantize_params(params, axes, kind: str):
    """Quantize a weight pytree per a same-structure axes tree whose
    leaves are the per-leaf amax reduction axis, or -1 to keep the leaf
    in full precision (biases, layer norms, MoE experts, anything a
    non-``astype`` path consumes)."""
    kind = resolve_quantization(kind)
    if kind is None:
        return params

    def _one(w, axis):
        if axis is None or axis < 0:
            return w
        return quantize_weight(w, int(axis), kind)

    return jax.tree.map(_one, params, axes)


def stack_blocks(blocks: list, axis: int = 1):
    """``np.stack`` generalized over plain arrays and ``QuantizedKV``
    records — the host-side landing paths (handoff adopt, host-tier
    promotion drain) stack per-block payloads into one scatter operand
    and must move scale planes alongside data."""
    first = blocks[0]
    if isinstance(first, QuantizedKV):
        return QuantizedKV(
            np.stack([b.data for b in blocks], axis=axis),
            np.stack([b.scale for b in blocks], axis=axis),
        )
    return np.stack(blocks, axis=axis)
