"""StandardAutoscaler: demand-driven scale-up, idle-timeout scale-down.

Equivalent of the reference's StandardAutoscaler + Monitor
(reference: python/ray/autoscaler/_private/autoscaler.py:171 update loop;
monitor.py:126 head-side process reading demand from the GCS). Runs as a
thread (or call update() manually in tests): reads per-node pending shapes
and availability from GCS heartbeats, bin-packs unmet demand onto node
types, launches through the NodeProvider, and terminates nodes idle past
the timeout (never below min_workers).
"""
from __future__ import annotations

import threading
import time

from ray_tpu._private.rpc import RpcClient
from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.autoscaler.resource_demand_scheduler import (
    NodeTypeConfig,
    get_nodes_to_launch,
)


class GcsPollingLoop:
    """Shared driver-loop plumbing for both autoscaler generations: a
    background update() ticker plus the GCS snapshot (nodes, demand shapes,
    available capacity) each pass consumes."""

    def __init__(self, gcs_address: str, update_interval_s: float,
                 thread_name: str):
        self.update_interval_s = update_interval_s
        self._gcs = RpcClient(gcs_address)
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None
        self._thread_name = thread_name
        self.last_status: dict = {}

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=self._thread_name
        )
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()
        self._gcs.close()

    def _loop(self) -> None:
        while not self._stopped.wait(self.update_interval_s):
            try:
                self.update()
            except Exception:  # noqa: BLE001 — the loop must survive
                if self._stopped.is_set():
                    return

    def update(self) -> dict:  # pragma: no cover - subclass responsibility
        raise NotImplementedError

    def _gcs_snapshot(self) -> tuple[dict[bytes, dict], list[dict], list[dict]]:
        nodes = {
            n["node_id"]: n
            for n in self._gcs.call("get_nodes")["nodes"]
            if n["alive"]
        }
        demands: list[dict] = []
        capacity: list[dict] = []
        for n in nodes.values():
            demands.extend(n.get("pending_shapes", []))
            capacity.append(dict(n.get("available", n["resources"])))
        return nodes, demands, capacity


class StandardAutoscaler(GcsPollingLoop):
    def __init__(
        self,
        gcs_address: str,
        provider: NodeProvider,
        node_types: dict[str, NodeTypeConfig],
        idle_timeout_s: float = 30.0,
        update_interval_s: float = 1.0,
    ):
        super().__init__(gcs_address, update_interval_s, "autoscaler")
        self.provider = provider
        self.node_types = dict(node_types)
        self.idle_timeout_s = idle_timeout_s
        self._idle_since: dict[str, float] = {}  # provider id -> ts
        self._launched_at: dict[str, float] = {}  # provider id -> ts
        self.launch_grace_s = 120.0  # registration deadline for new nodes

    # -- one reconcile pass (reference: autoscaler.py:171 update) --

    def update(self) -> dict:
        nodes, demands, capacity = self._gcs_snapshot()
        if hasattr(self.provider, "set_cluster_nodes"):
            # cloud providers resolve internal_id from node labels: hand
            # them the snapshot we already pulled instead of one RPC per
            # managed node per tick
            self.provider.set_cluster_nodes(list(nodes.values()))
        managed = self.provider.non_terminated_nodes()
        counts: dict[str, int] = {}
        for pid, t in managed.items():
            counts[t] = counts.get(t, 0) + 1

        to_launch = get_nodes_to_launch(
            self.node_types, counts, capacity, demands
        )
        for t, count in to_launch.items():
            for _ in range(count):
                pid = self.provider.create_node(
                    t, dict(self.node_types[t].resources)
                )
                self._launched_at[pid] = time.monotonic()

        terminated = self._scale_down(nodes, managed, counts, to_launch)
        self.last_status = {
            "demand_shapes": len(demands),
            "launched": dict(to_launch),
            "terminated": terminated,
            "managed_nodes": len(managed),
        }
        return self.last_status

    def _scale_down(self, nodes, managed, counts, just_launched) -> list[str]:
        """Terminate provider nodes idle past the timeout (reference:
        autoscaler idle node termination; keeps min_workers per type)."""
        now = time.monotonic()
        terminated: list[str] = []
        for pid, t in list(managed.items()):
            internal = self.provider.internal_id(pid)
            info = nodes.get(internal)
            if info is None:
                # not in the GCS: failed/slow launch. Terminate past the
                # grace deadline or the node leaks forever while eating the
                # type's max_workers budget.
                launched = self._launched_at.setdefault(pid, now)
                if now - launched > self.launch_grace_s:
                    self.provider.terminate_node(pid)
                    self._launched_at.pop(pid, None)
                    counts[t] = counts.get(t, 0) - 1
                    terminated.append(pid)
                continue
            self._launched_at.pop(pid, None)  # registered — clear the clock
            avail = info.get("available", info["resources"])
            busy = (
                any(avail.get(k, 0) < v for k, v in info["resources"].items())
                or info.get("load", 0) > 0
                or info.get("pending_shapes")
            )
            if busy:
                self._idle_since.pop(pid, None)
                continue
            since = self._idle_since.setdefault(pid, now)
            if now - since < self.idle_timeout_s:
                continue
            cfg = self.node_types.get(t)
            floor = cfg.min_workers if cfg else 0
            if counts.get(t, 0) + just_launched.get(t, 0) <= floor:
                continue
            self.provider.terminate_node(pid)
            counts[t] = counts.get(t, 0) - 1
            self._idle_since.pop(pid, None)
            terminated.append(pid)
        return terminated
