"""NodeProvider interface + fake in-process provider.

Equivalent of the reference's pluggable provider layer
(reference: python/ray/autoscaler/node_provider.py:13 NodeProvider;
fake multi-node provider python/ray/autoscaler/_private/fake_multi_node/
node_provider.py:237 used for autoscaler tests without a cloud,
SURVEY.md §4.3). A cloud provider implements the same 4 methods against
its VM API (the reference's GCP TPU pods: autoscaler/gcp/tpu.yaml).
"""
from __future__ import annotations

import threading
import uuid
from typing import Any


class NodeProvider:
    """Minimal provider contract (reference: node_provider.py:13)."""

    def create_node(self, node_type: str, resources: dict[str, float]) -> str:
        """Launch one node; returns provider node id."""
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> dict[str, str]:
        """provider node id -> node_type."""
        raise NotImplementedError

    def internal_id(self, node_id: str) -> bytes | None:
        """Cluster node id (GCS) for a provider node, once registered."""
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Backs provider nodes with in-process raylets on the test Cluster
    (reference: RAY_FAKE_CLUSTER=1 fake_multi_node provider)."""

    def __init__(self, cluster):
        self._cluster = cluster
        self._lock = threading.Lock()
        self._nodes: dict[str, Any] = {}  # provider id -> (type, raylet)

    def create_node(self, node_type: str, resources: dict[str, float]) -> str:
        res = dict(resources)
        raylet = self._cluster.add_node(
            num_cpus=res.pop("CPU", 1),
            num_tpus=res.pop("TPU", 0),
            resources=res,
            labels={"rt-node-type": node_type, "rt-autoscaled": "1"},
        )
        pid = f"fake-{uuid.uuid4().hex[:8]}"
        with self._lock:
            self._nodes[pid] = (node_type, raylet)
        return pid

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            entry = self._nodes.pop(node_id, None)
        if entry is not None:
            self._cluster.remove_node(entry[1])

    def non_terminated_nodes(self) -> dict[str, str]:
        with self._lock:
            return {pid: t for pid, (t, _r) in self._nodes.items()}

    def internal_id(self, node_id: str) -> bytes | None:
        with self._lock:
            entry = self._nodes.get(node_id)
        return entry[1].node_id.binary() if entry else None
