"""Autoscaler v2 — the instance-manager / scheduler / reconciler split.

Equivalent of the reference's autoscaler v2 (reference:
python/ray/autoscaler/v2/ — instance_manager/instance_manager.py holds a
versioned instance table behind an update API; instance lifecycle states
instance_manager/common.py InstanceUtil; the Reconciler
(instance_manager/reconciler.py) converges the table against cloud-provider
and Ray-cluster reality each tick; scheduler.py computes desired
instances from demand). StandardAutoscaler (autoscaler.py) remains the
merged v1; this module separates the concerns so each is independently
testable and replaceable:

  * InstanceManager — the ONLY component that mutates instance state; a
    versioned table with compare-and-swap updates (the reference's
    protocol boundary, gRPC there, in-process here).
  * Reconciler — pure logic: given the table + provider view + GCS view +
    demand, emits InstanceUpdates and provider actions.
  * AutoscalerV2 — the driver loop wiring them to a NodeProvider and GCS.

Lifecycle: QUEUED → REQUESTED → ALLOCATED → RAY_RUNNING → TERMINATING →
TERMINATED (plus ALLOCATION_FAILED for launch-deadline misses).
"""
from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

from ray_tpu.autoscaler.autoscaler import GcsPollingLoop
from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.autoscaler.resource_demand_scheduler import (
    NodeTypeConfig,
    get_nodes_to_launch,
)

# instance lifecycle states (reference: instance_manager/common.py)
QUEUED = "QUEUED"
REQUESTED = "REQUESTED"
ALLOCATED = "ALLOCATED"
RAY_RUNNING = "RAY_RUNNING"
TERMINATING = "TERMINATING"
TERMINATED = "TERMINATED"
ALLOCATION_FAILED = "ALLOCATION_FAILED"

_LIVE_STATES = (QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING, TERMINATING)


@dataclass
class Instance:
    instance_id: str
    node_type: str
    status: str = QUEUED
    provider_id: Optional[str] = None
    ray_node_id: Optional[bytes] = None
    status_since: float = field(default_factory=time.monotonic)
    idle_since: Optional[float] = None


@dataclass
class InstanceUpdate:
    instance_id: str
    new_status: str
    provider_id: Optional[str] = None
    ray_node_id: Optional[bytes] = None
    idle_since: Optional[float] = None


class InstanceManager:
    """Versioned instance table; updates go through update_instance_states
    with an expected version (compare-and-swap, the reference's protocol:
    instance_manager.py UpdateInstanceManagerStateRequest.expected_version).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._instances: dict[str, Instance] = {}
        self._version = 0

    def get_state(self) -> tuple[int, dict[str, Instance]]:
        with self._lock:
            return self._version, {
                k: Instance(**vars(v)) for k, v in self._instances.items()
            }

    def add_instances(self, node_types: list[str],
                      expected_version: int) -> bool:
        updates = []
        for t in node_types:
            iid = uuid.uuid4().hex[:12]
            updates.append((iid, Instance(instance_id=iid, node_type=t)))
        with self._lock:
            if expected_version != self._version:
                return False
            for iid, inst in updates:
                self._instances[iid] = inst
            self._version += 1
            return True

    # terminal instances older than this are garbage-collected (the
    # reference likewise GCs stopped instances from the table)
    TERMINAL_RETENTION_S = 300.0

    def update_instance_states(self, updates: list[InstanceUpdate],
                               expected_version: int) -> bool:
        with self._lock:
            if expected_version != self._version:
                return False
            for u in updates:
                inst = self._instances.get(u.instance_id)
                if inst is None:
                    continue
                if u.new_status != inst.status:
                    inst.status = u.new_status
                    inst.status_since = time.monotonic()
                if u.provider_id is not None:
                    inst.provider_id = u.provider_id
                if u.ray_node_id is not None:
                    inst.ray_node_id = u.ray_node_id
                inst.idle_since = u.idle_since
            self._version += 1
            # GC: the table must not grow with cluster churn
            cutoff = time.monotonic() - self.TERMINAL_RETENTION_S
            dead = [
                k for k, i in self._instances.items()
                if i.status in (TERMINATED, ALLOCATION_FAILED)
                and i.status_since < cutoff
            ]
            for k in dead:
                del self._instances[k]
            return True


class Reconciler:
    """One converge pass (reference: reconciler.py Reconcile). Pure with
    respect to the instance table: reads a snapshot, returns the updates
    and performs provider actions."""

    def __init__(self, node_types: dict[str, NodeTypeConfig],
                 idle_timeout_s: float = 30.0, launch_grace_s: float = 120.0):
        self.node_types = dict(node_types)
        self.idle_timeout_s = idle_timeout_s
        self.launch_grace_s = launch_grace_s

    def step(self, im: InstanceManager, provider: NodeProvider,
             gcs_nodes: dict[bytes, dict], demands: list[dict],
             capacity: list[dict]) -> dict:
        version, instances = im.get_state()
        updates: list[InstanceUpdate] = []
        now = time.monotonic()
        actions = {"launched": 0, "terminated": 0, "failed": 0}

        live_by_type: dict[str, int] = {}
        for inst in instances.values():
            if inst.status in _LIVE_STATES:
                live_by_type[inst.node_type] = (
                    live_by_type.get(inst.node_type, 0) + 1)

        created: list[str] = []  # provider ids from THIS pass (compensation)
        for inst in instances.values():
            if inst.status == QUEUED:
                # request from the cloud provider
                pid = provider.create_node(
                    inst.node_type,
                    dict(self.node_types[inst.node_type].resources),
                )
                created.append(pid)
                updates.append(InstanceUpdate(
                    inst.instance_id, ALLOCATED, provider_id=pid))
                actions["launched"] += 1
            elif inst.status == ALLOCATED:
                rid = provider.internal_id(inst.provider_id)
                info = gcs_nodes.get(rid) if rid else None
                if info is not None:
                    updates.append(InstanceUpdate(
                        inst.instance_id, RAY_RUNNING, ray_node_id=rid))
                elif now - inst.status_since > self.launch_grace_s:
                    provider.terminate_node(inst.provider_id)
                    updates.append(InstanceUpdate(
                        inst.instance_id, ALLOCATION_FAILED))
                    actions["failed"] += 1
            elif inst.status == RAY_RUNNING:
                info = gcs_nodes.get(inst.ray_node_id)
                if info is None:
                    # node died outside our control
                    updates.append(InstanceUpdate(inst.instance_id, TERMINATED))
                    continue
                avail = info.get("available", info["resources"])
                busy = (
                    any(avail.get(k, 0) < v
                        for k, v in info["resources"].items())
                    or info.get("load", 0) > 0
                    or info.get("pending_shapes")
                )
                if busy:
                    updates.append(InstanceUpdate(
                        inst.instance_id, RAY_RUNNING, idle_since=None))
                    continue
                idle_since = inst.idle_since or now
                floor = self.node_types[inst.node_type].min_workers
                if (now - idle_since >= self.idle_timeout_s
                        and live_by_type.get(inst.node_type, 0) > floor):
                    updates.append(InstanceUpdate(
                        inst.instance_id, TERMINATING))
                    live_by_type[inst.node_type] -= 1
                else:
                    updates.append(InstanceUpdate(
                        inst.instance_id, RAY_RUNNING, idle_since=idle_since))
            elif inst.status == TERMINATING:
                provider.terminate_node(inst.provider_id)
                updates.append(InstanceUpdate(inst.instance_id, TERMINATED))
                actions["terminated"] += 1

        if updates:
            if not im.update_instance_states(updates, version):
                # another writer won the CAS mid-pass: our provider actions
                # are untracked — COMPENSATE by terminating what we just
                # created (the instances stay QUEUED and relaunch next
                # tick), and skip scale-up this pass
                for pid in created:
                    provider.terminate_node(pid)
                actions["cas_lost"] = True
                return actions
            version, instances = im.get_state()

        # scale up: unmet demand → new QUEUED instances
        counts = {
            t: sum(1 for i in instances.values()
                   if i.node_type == t and i.status in _LIVE_STATES)
            for t in self.node_types
        }
        to_launch = get_nodes_to_launch(
            self.node_types, counts, capacity, demands)
        queue: list[str] = []
        for t, n in to_launch.items():
            queue.extend([t] * n)
        if queue:
            # CAS failure here loses nothing irreversible: the demand is
            # still unmet and re-queues next tick
            im.add_instances(queue, version)
        actions["queued"] = len(queue)
        return actions


class AutoscalerV2(GcsPollingLoop):
    """Driver loop: GCS view + demand in, reconciler pass per tick."""

    def __init__(self, gcs_address: str, provider: NodeProvider,
                 node_types: dict[str, NodeTypeConfig],
                 idle_timeout_s: float = 30.0,
                 update_interval_s: float = 1.0):
        super().__init__(gcs_address, update_interval_s, "autoscaler-v2")
        self.im = InstanceManager()
        self.reconciler = Reconciler(node_types, idle_timeout_s)
        self.provider = provider
        # serializes the background ticker against manual update() calls so
        # reconcile passes never interleave (a lost CAS mid-pass would
        # otherwise force provider-side compensation)
        self._update_lock = threading.Lock()

    def update(self) -> dict:
        with self._update_lock:
            nodes, demands, capacity = self._gcs_snapshot()
            self.last_status = self.reconciler.step(
                self.im, self.provider, nodes, demands, capacity)
            return self.last_status
