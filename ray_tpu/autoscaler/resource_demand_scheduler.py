"""Demand bin-packing: pending resource shapes → nodes to launch.

Equivalent of the reference's ResourceDemandScheduler
(reference: python/ray/autoscaler/_private/resource_demand_scheduler.py:102
get_nodes_to_launch, :170 bin-packing over node types). TPU-first: node
types describe whole slices (e.g. a v5e-4 host = {"CPU": 8, "TPU": 4});
a TPU-shaped demand packs onto slice types only, so scale-up happens in
slice granularity (SURVEY.md §7 item 11).
"""
from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class NodeTypeConfig:
    resources: dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


def _fits(shape: dict[str, float], capacity: dict[str, float]) -> bool:
    return all(capacity.get(k, 0.0) >= v for k, v in shape.items() if v > 0)


def _subtract(capacity: dict[str, float], shape: dict[str, float]) -> None:
    for k, v in shape.items():
        capacity[k] = capacity.get(k, 0.0) - v


def get_nodes_to_launch(
    node_types: dict[str, NodeTypeConfig],
    current_counts: dict[str, int],
    available_capacity: list[dict[str, float]],
    pending_demands: list[dict[str, float]],
) -> dict[str, int]:
    """Bin-pack unmet demands onto hypothetical new nodes.

    available_capacity: one dict per live node (its CURRENT free resources).
    Returns {node_type: count_to_launch}, bounded by per-type max_workers.
    """
    to_launch: dict[str, int] = {}
    counts = dict(current_counts)

    # respect min_workers first
    for t, cfg in node_types.items():
        deficit = cfg.min_workers - counts.get(t, 0)
        if deficit > 0:
            to_launch[t] = to_launch.get(t, 0) + deficit
            counts[t] = counts.get(t, 0) + deficit

    capacity = [dict(c) for c in available_capacity]
    # capacity of nodes we just decided to launch
    for t, n in to_launch.items():
        capacity.extend(dict(node_types[t].resources) for _ in range(n))

    # largest demands first pack tighter (standard first-fit-decreasing)
    demands = sorted(
        (d for d in pending_demands if d),
        key=lambda d: -sum(d.values()),
    )
    for shape in demands:
        placed = False
        for cap in capacity:
            if _fits(shape, cap):
                _subtract(cap, shape)
                placed = True
                break
        if placed:
            continue
        # launch the smallest node type that can hold the shape
        candidates = [
            (sum(cfg.resources.values()), t)
            for t, cfg in node_types.items()
            if _fits(shape, cfg.resources)
            and counts.get(t, 0) < cfg.max_workers
        ]
        if not candidates:
            continue  # infeasible or at the cap — surfaced via status
        _, t = min(candidates)
        to_launch[t] = to_launch.get(t, 0) + 1
        counts[t] = counts.get(t, 0) + 1
        new_cap = dict(node_types[t].resources)
        _subtract(new_cap, shape)
        capacity.append(new_cap)
    return to_launch
