"""GCP NodeProvider — Compute Engine VMs and Cloud TPU VM slices.

Equivalent of the reference's GCP provider (reference:
python/ray/autoscaler/_private/gcp/node_provider.py:61 GCPNodeProvider —
routes nodes between the Compute API and the TPU API by node_config
shape; config.py client construction; autoscaler/gcp/example-tpu-pod.yaml
for TPU pod node types). TPU-first: a node type whose node_config carries
``acceleratorType`` becomes a Cloud TPU VM node
(tpu.googleapis.com/v2 projects.locations.nodes — v4/v5e/v5p slices);
everything else is a Compute Engine instance.

The REST transport is injectable (``api``), so the provider's full
lifecycle — create/list/terminate for both services, label filtering,
internal-id resolution — unit-tests against a mocked API with no cloud
access, exactly how the reference tests its providers. The real
transport authenticates via the GCE metadata server (the only
credential source that needs no extra dependency on a cloud VM).
"""
from __future__ import annotations

import json
import threading
import uuid
from typing import Any, Callable, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.autoscaler.resource_demand_scheduler import NodeTypeConfig

COMPUTE_ROOT = "https://compute.googleapis.com/compute/v1"
TPU_ROOT = "https://tpu.googleapis.com/v2"
_METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                       "instance/service-accounts/default/token")

# request_fn(method, url, body_dict_or_None, headers) -> response dict
RequestFn = Callable[[str, str, Optional[dict], dict], dict]


def _default_request_fn(method: str, url: str, body: Optional[dict],
                        headers: dict) -> dict:
    import urllib.request

    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json",
                                          **headers})
    with urllib.request.urlopen(req, timeout=60) as resp:
        payload = resp.read()
    return json.loads(payload) if payload else {}


class GcpApi:
    """Thin REST client over the two services the provider needs.

    ``request_fn`` is the seam: tests inject a recording fake; production
    uses urllib with a metadata-server bearer token.
    """

    def __init__(self, project: str, zone: str,
                 request_fn: RequestFn | None = None):
        self.project = project
        self.zone = zone
        self._request_fn = request_fn or _default_request_fn
        self._token: str | None = None
        self._token_expiry = 0.0
        self._token_lock = threading.Lock()

    # -- auth --

    def _headers(self) -> dict:
        import time

        if self._request_fn is not _default_request_fn:
            return {}  # injected transports own their auth
        with self._token_lock:
            # metadata tokens live ~1h; refresh with a 5-minute margin so
            # a long-running autoscaler never sails into 401s
            if self._token is None or time.monotonic() > self._token_expiry:
                tok = self._request_fn(
                    "GET", _METADATA_TOKEN_URL, None,
                    {"Metadata-Flavor": "Google"})
                self._token = tok["access_token"]
                self._token_expiry = (time.monotonic()
                                      + float(tok.get("expires_in", 3600))
                                      - 300.0)
            return {"Authorization": f"Bearer {self._token}"}

    def _call(self, method: str, url: str, body: Optional[dict] = None) -> dict:
        return self._request_fn(method, url, body, self._headers())

    # -- Compute Engine instances --

    def insert_instance(self, body: dict) -> dict:
        return self._call(
            "POST",
            f"{COMPUTE_ROOT}/projects/{self.project}/zones/{self.zone}"
            "/instances", body)

    def delete_instance(self, name: str) -> dict:
        return self._call(
            "DELETE",
            f"{COMPUTE_ROOT}/projects/{self.project}/zones/{self.zone}"
            f"/instances/{name}")

    def list_instances(self, label_filter: str) -> list[dict]:
        base = (f"{COMPUTE_ROOT}/projects/{self.project}/zones/{self.zone}"
                f"/instances?filter={label_filter}")
        return self._paged("GET", base, "items")

    def _paged(self, method: str, base_url: str, items_key: str) -> list[dict]:
        """Follow nextPageToken — a >1-page cluster must not be silently
        truncated (invisible nodes escape both counting and termination)."""
        out: list[dict] = []
        token = None
        while True:
            sep = "&" if "?" in base_url else "?"
            url = base_url + (f"{sep}pageToken={token}" if token else "")
            page = self._call(method, url)
            out.extend(page.get(items_key, []))
            token = page.get("nextPageToken")
            if not token:
                return out

    # -- Cloud TPU VM nodes --

    def _tpu_parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def create_tpu_node(self, node_id: str, body: dict) -> dict:
        return self._call(
            "POST",
            f"{TPU_ROOT}/{self._tpu_parent()}/nodes?nodeId={node_id}", body)

    def delete_tpu_node(self, name: str) -> dict:
        return self._call(
            "DELETE", f"{TPU_ROOT}/{self._tpu_parent()}/nodes/{name}")

    def list_tpu_nodes(self) -> list[dict]:
        return self._paged(
            "GET", f"{TPU_ROOT}/{self._tpu_parent()}/nodes", "nodes")


class GCPNodeProvider(NodeProvider):
    """Provider-id namespace: ``gce:{name}`` / ``tpu:{name}``. Every node
    carries labels identifying the cluster and node type, and the boot
    metadata passes the provider id through so the raylet registers with
    a ``rt-provider-id`` node label — which is how internal_id() maps a
    cloud VM back to its GCS node entry."""

    def __init__(self, config: dict, api: GcpApi | None = None,
                 list_nodes_fn: Callable[[], list[dict]] | None = None):
        self.cluster_name = config["cluster_name"]
        provider_cfg = config["provider"]
        self.project = provider_cfg["project_id"]
        self.zone = provider_cfg["availability_zone"]
        self.node_type_configs: dict[str, dict] = {
            name: dict(cfg.get("node_config", {}))
            for name, cfg in config.get("available_node_types", {}).items()
        }
        self.api = api or GcpApi(self.project, self.zone)
        self._list_nodes_fn = list_nodes_fn
        self._cluster_nodes: list[dict] | None = None  # pushed snapshot
        # create() is ASYNC on GCP: a just-created node isn't in the list
        # API yet, and the autoscaler recounts from the list every tick —
        # without local pending tracking it would double-launch slices
        self._pending: dict[str, tuple[str, float]] = {}
        self._pending_ttl_s = 300.0
        self._lock = threading.Lock()

    # -- NodeProvider contract --

    def create_node(self, node_type: str, resources: dict[str, float]) -> str:
        node_config = self.node_type_configs.get(node_type)
        if node_config is None:
            raise ValueError(
                f"unknown node type {node_type!r}; configured: "
                f"{sorted(self.node_type_configs)}")
        name = (f"rt-{self.cluster_name}-{node_type}-"
                f"{uuid.uuid4().hex[:8]}").lower().replace("_", "-")
        labels = {
            "rt-cluster": self.cluster_name,
            "rt-node-type": node_type,
        }
        if "acceleratorType" in node_config:
            # Cloud TPU VM slice (v4-8, v5litepod-4, ...): the TPU API,
            # not a Compute instance
            body = {
                "acceleratorType": node_config["acceleratorType"],
                "runtimeVersion": node_config.get(
                    "runtimeVersion", "tpu-ubuntu2204-base"),
                "labels": labels,
                "metadata": {"rt-provider-id": f"tpu:{name}"},
            }
            if "networkConfig" in node_config:
                body["networkConfig"] = node_config["networkConfig"]
            self.api.create_tpu_node(name, body)
            return self._track_pending(f"tpu:{name}", node_type)
        body = {
            "name": name,
            "machineType": (f"zones/{self.zone}/machineTypes/"
                            f"{node_config.get('machineType', 'n2-standard-4')}"),
            "disks": node_config.get("disks", [{
                "boot": True, "autoDelete": True,
                "initializeParams": {"diskSizeGb": 50},
            }]),
            "networkInterfaces": node_config.get(
                "networkInterfaces", [{"network": "global/networks/default"}]),
            "labels": labels,
            "metadata": {"items": [
                {"key": "rt-provider-id", "value": f"gce:{name}"},
            ]},
        }
        self.api.insert_instance(body)
        return self._track_pending(f"gce:{name}", node_type)

    def _track_pending(self, pid: str, node_type: str) -> str:
        import time

        with self._lock:
            self._pending[pid] = (node_type, time.monotonic())
        return pid

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            self._pending.pop(node_id, None)
        kind, _, name = node_id.partition(":")
        if kind == "tpu":
            self.api.delete_tpu_node(name)
        else:
            self.api.delete_instance(name)

    def non_terminated_nodes(self) -> dict[str, str]:
        import time

        out: dict[str, str] = {}
        label_filter = f"labels.rt-cluster={self.cluster_name}"
        for inst in self.api.list_instances(label_filter):
            if inst.get("status") in ("STOPPING", "TERMINATED", "SUSPENDED"):
                continue
            labels = inst.get("labels", {})
            if labels.get("rt-cluster") != self.cluster_name:
                continue
            out[f"gce:{inst['name']}"] = labels.get("rt-node-type", "")
        for node in self.api.list_tpu_nodes():
            labels = node.get("labels", {})
            if labels.get("rt-cluster") != self.cluster_name:
                continue
            # PREEMPTED is terminal for TPU slices: keeping it "alive"
            # would hold the type's max_workers budget and block the
            # replacement launch
            if node.get("state") in ("DELETING", "TERMINATED", "STOPPED",
                                     "PREEMPTED", "STOPPING"):
                continue
            # TPU node names come back fully qualified
            name = node.get("name", "").rsplit("/", 1)[-1]
            out[f"tpu:{name}"] = labels.get("rt-node-type", "")
        # merge creates still in flight (async API, not yet listed)
        now = time.monotonic()
        with self._lock:
            for pid, (node_type, ts) in list(self._pending.items()):
                if pid in out or now - ts > self._pending_ttl_s:
                    del self._pending[pid]
                elif pid not in out:
                    out[pid] = node_type
        return out

    def set_cluster_nodes(self, nodes: list[dict]) -> None:
        """Autoscaler hook: push the GCS node snapshot it already holds,
        sparing internal_id() a per-node RPC per tick (and any dependence
        on ray_tpu.init() in the autoscaler process)."""
        with self._lock:
            self._cluster_nodes = list(nodes)

    def internal_id(self, node_id: str) -> bytes | None:
        """GCS node whose registration labels carry this provider id."""
        with self._lock:
            snapshot = self._cluster_nodes
        if snapshot is None:
            if self._list_nodes_fn is not None:
                snapshot = self._list_nodes_fn()
            else:
                snapshot = _live_cluster_nodes()
        for n in snapshot:
            if n.get("labels", {}).get("rt-provider-id") == node_id:
                return n["node_id"]
        return None


def _live_cluster_nodes() -> list[dict]:
    try:
        import ray_tpu

        return ray_tpu.nodes()
    except Exception:  # noqa: BLE001 — no driver in this process
        return []


def load_cluster_config(path: str) -> dict:
    """Parse a reference-style cluster YAML (cluster_name / provider /
    available_node_types — the autoscaler/gcp/example-tpu-pod.yaml shape)
    into {provider_config, node_types, max_workers}. `node_types` are
    NodeTypeConfig for StandardAutoscaler; `provider_config` feeds
    GCPNodeProvider."""
    import yaml

    with open(path) as f:
        raw = yaml.safe_load(f)
    for key in ("cluster_name", "provider", "available_node_types"):
        if key not in raw:
            raise ValueError(f"cluster config missing {key!r}")
    node_types = {
        name: NodeTypeConfig(
            resources=dict(cfg.get("resources", {})),
            min_workers=int(cfg.get("min_workers", 0)),
            max_workers=int(cfg.get("max_workers",
                                    raw.get("max_workers", 10))),
        )
        for name, cfg in raw["available_node_types"].items()
    }
    return {
        "cluster_name": raw["cluster_name"],
        "provider": raw["provider"],
        "available_node_types": raw["available_node_types"],
        "node_types": node_types,
        "max_workers": int(raw.get("max_workers", 10)),
    }
