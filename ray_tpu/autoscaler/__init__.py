"""ray_tpu.autoscaler — demand-driven cluster scaling.

Equivalent of the reference's autoscaler (reference: python/ray/autoscaler —
SURVEY.md §2.2 P10/P11). Node types are whole TPU slices, so scale-up is
slice-granular; providers are pluggable (fake in-process provider for tests,
cloud providers implement the same 4-method contract).
"""
from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.v2 import AutoscalerV2, InstanceManager, Reconciler
from ray_tpu.autoscaler.gcp import GCPNodeProvider, load_cluster_config
from ray_tpu.autoscaler.node_provider import FakeMultiNodeProvider, NodeProvider
from ray_tpu.autoscaler.resource_demand_scheduler import (
    NodeTypeConfig,
    get_nodes_to_launch,
)

__all__ = [
    "AutoscalerV2",
    "FakeMultiNodeProvider",
    "GCPNodeProvider",
    "load_cluster_config",
    "InstanceManager",
    "Reconciler",
    "NodeProvider",
    "NodeTypeConfig",
    "StandardAutoscaler",
    "get_nodes_to_launch",
]
