// C++ util substrate shared by the native components (the N18 analog of
// the reference's src/ray/util/ — structured event log event.h/.cc,
// exponential_backoff.h, throttler.h, counter_map.h; same roles, sized
// to what the in-tree daemons actually use).
//
// Header-only on purpose: the native components build as single
// translation units through native_build.py's content-hash cache, and a
// separate .so would complicate that for zero benefit at this size.
#pragma once

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <ctime>
#include <mutex>
#include <string>
#include <unordered_map>

namespace rt_util {

// ---------------------------------------------------------------------
// Structured NDJSON event log (reference: util/event.h RayEvent — one
// JSON object per line with severity, timestamp, label and kv fields).
// Destination: $RT_EVENT_LOG file when set, else stderr. Thread-safe.
// ---------------------------------------------------------------------
class StructuredLog {
 public:
  static StructuredLog &Instance() {
    static StructuredLog inst;
    return inst;
  }

  // Emit {"ts":..., "severity":..., "label":..., <fields>}. `fields`
  // is a pre-rendered JSON fragment like "\"id\":\"ab\",\"bytes\":5"
  // (callers own their escaping; labels/severities are code constants).
  void Emit(const char *severity, const char *label,
            const std::string &fields) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!out_) return;
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    fprintf(out_, "{\"ts\":%lld.%03ld,\"severity\":\"%s\",\"label\":\"%s\"%s%s}\n",
            (long long)ts.tv_sec, ts.tv_nsec / 1000000, severity, label,
            fields.empty() ? "" : ",", fields.c_str());
    fflush(out_);
  }

 private:
  StructuredLog() {
    const char *path = getenv("RT_EVENT_LOG");
    out_ = path && *path ? fopen(path, "a") : stderr;
    if (!out_) out_ = stderr;
  }
  std::mutex mu_;
  FILE *out_;
};

inline void Event(const char *severity, const char *label,
                  const std::string &fields = "") {
  StructuredLog::Instance().Emit(severity, label, fields);
}

// JSON string escaping for UNTRUSTED values (paths, ids) interpolated
// into event fields — callers of Event() own their escaping.
inline std::string JsonEscape(const std::string &in) {
  std::string out;
  out.reserve(in.size() + 8);
  for (unsigned char c : in) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += (char)c;
        }
    }
  }
  return out;
}

// ---------------------------------------------------------------------
// Deterministic exponential backoff (reference: util/exponential_backoff.h
// — same multiplier/cap contract, no jitter: callers that want jitter
// add it, and deterministic delays keep tests exact).
// ---------------------------------------------------------------------
class ExponentialBackoff {
 public:
  ExponentialBackoff(uint64_t initial_ms, double multiplier, uint64_t max_ms)
      : initial_ms_(initial_ms), multiplier_(multiplier), max_ms_(max_ms),
        current_ms_(initial_ms) {}

  uint64_t Next() {
    uint64_t v = current_ms_;
    double n = (double)current_ms_ * multiplier_;
    current_ms_ = n > (double)max_ms_ ? max_ms_ : (uint64_t)n;
    return v;
  }

  void Reset() { current_ms_ = initial_ms_; }
  uint64_t Current() const { return current_ms_; }

 private:
  uint64_t initial_ms_;
  double multiplier_;
  uint64_t max_ms_;
  uint64_t current_ms_;
};

// ---------------------------------------------------------------------
// Event-rate throttler (reference: util/throttler.h): AbleToRun() is
// true at most once per period. Used so pressure paths (spill/evict
// storms) log a bounded number of lines, not one per object.
// ---------------------------------------------------------------------
class Throttler {
 public:
  explicit Throttler(uint64_t period_ms) : period_ms_(period_ms) {}

  bool AbleToRun() {
    uint64_t now = NowMs();
    std::lock_guard<std::mutex> lk(mu_);
    if (now - last_run_ms_ >= period_ms_) {
      last_run_ms_ = now;
      return true;
    }
    return false;
  }

  static uint64_t NowMs() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1000 + (uint64_t)(ts.tv_nsec / 1000000);
  }

 private:
  uint64_t period_ms_;
  uint64_t last_run_ms_ = 0;
  std::mutex mu_;
};

// ---------------------------------------------------------------------
// Counter map (reference: util/counter_map.h): named monotonic counters
// a daemon can dump as one structured event (e.g. at shutdown).
// ---------------------------------------------------------------------
class CounterMap {
 public:
  void Inc(const std::string &key, uint64_t by = 1) {
    std::lock_guard<std::mutex> lk(mu_);
    counts_[key] += by;
  }

  std::string ToJsonFields() const {
    std::lock_guard<std::mutex> lk(mu_);
    std::string out;
    for (auto &kv : counts_) {
      if (!out.empty()) out += ",";
      out += "\"" + kv.first + "\":" + std::to_string(kv.second);
    }
    return out;
  }

 private:
  mutable std::mutex mu_;
  std::unordered_map<std::string, uint64_t> counts_;
};

}  // namespace rt_util
