// Native scheduling core: feasibility + node selection over dense
// resource matrices.
//
// Equivalent of the reference's C++ scheduling policies
// (reference: src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.h:50
// hybrid pack-then-spread; scheduling_policy spread variant;
// cluster_resource_data.h dense NodeResources). The Python layer
// (ray_tpu/_private/scheduler.py) lowers its node dicts into dense
// [n_nodes x n_res] matrices and calls rt_pick_node; semantics are kept
// identical to the Python implementation, which doubles as the test oracle.
//
// Build: g++ -O2 -shared -fPIC -o libray_tpu_sched.so sched.cpp
#include <cstdint>

namespace {

constexpr double kEps = 1e-9;

inline bool Fits(const double* demand, const double* avail, int n_res) {
  for (int r = 0; r < n_res; ++r) {
    if (demand[r] > 0 && avail[r] + kEps < demand[r]) return false;
  }
  return true;
}

// available CPU fraction — the load signal the Python policy uses. A
// missing/out-of-range CPU column reads as fully available rather than
// indexing out of bounds (found by the ASAN fuzz driver in
// tests/test_sanitizers.py; the same bounds discipline rt_pick_node
// already applies to local_index).
inline double AvailFrac(const double* avail, const double* total, int cpu_col,
                        int n_res) {
  if (cpu_col < 0 || cpu_col >= n_res) return 1.0;
  double cpu_total = total[cpu_col];
  if (cpu_total == 0) cpu_total = 1.0;
  return avail[cpu_col] / cpu_total;
}

}  // namespace

extern "C" {

// strategy: 0 = default/hybrid (local first, else most-loaded feasible —
//               pack), 1 = spread (least-loaded feasible)
// Returns the chosen node row index, or -1 if infeasible everywhere.
int rt_pick_node(const double* demand, int n_res, const double* avail,
                 const double* total, const uint8_t* alive, int n_nodes,
                 int cpu_col, int strategy, int local_index) {
  if (n_nodes <= 0 || n_res <= 0) return -1;
  // hybrid: local node wins outright when feasible
  if (strategy == 0 && local_index >= 0 && local_index < n_nodes &&
      alive[local_index] &&
      Fits(demand, avail + (int64_t)local_index * n_res, n_res)) {
    return local_index;
  }
  int best = -1;
  double best_frac = 0;
  for (int i = 0; i < n_nodes; ++i) {
    if (!alive[i]) continue;
    const double* a = avail + (int64_t)i * n_res;
    if (!Fits(demand, a, n_res)) continue;
    double frac = AvailFrac(a, total + (int64_t)i * n_res, cpu_col, n_res);
    if (best == -1 ||
        (strategy == 1 ? frac > best_frac : frac < best_frac)) {
      best = i;
      best_frac = frac;
    }
  }
  return best;
}

// Batch feasibility check: out[i] = 1 if demand fits node i's availability.
// Used by the dispatch loop to prefilter queued work without Python dict
// traffic.
void rt_feasible_mask(const double* demand, int n_res, const double* avail,
                      const uint8_t* alive, int n_nodes, uint8_t* out) {
  for (int i = 0; i < n_nodes; ++i) {
    out[i] = alive[i] && Fits(demand, avail + (int64_t)i * n_res, n_res);
  }
}

}  // extern "C"
