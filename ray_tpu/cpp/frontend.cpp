// C++ frontend for the ray_tpu runtime.
//
// Equivalent in role to the reference's C++ worker/API layer (reference:
// cpp/include/ray/api.h — ray::Init / ray::Put / ray::Get /
// ray::Task(...).Remote() over the core worker; cross-language calls use
// function DESCRIPTORS plus msgpack-serialized values,
// src/ray/common/function_descriptor.h). Here the same three planes are
// spoken natively:
//
//   * control plane  — msgpack-framed RPC to the GCS and raylet
//                      (_private/rpc.py wire format, incl. the _handshake
//                      protocol check from _private/schema.py);
//   * object plane   — the shm store daemon's unix-socket protocol
//                      (cpp/store.cpp framing), values mmap'd directly;
//   * task plane     — task specs built as msgpack maps with a
//                      "function_desc" ("module:callable") instead of a
//                      pickled blob, and XLANG (msgpack) args/returns —
//                      the exact cross-language contract the Python worker
//                      honors (_private/worker.py _load_function,
//                      _private/serialization.py XLANG envelope).
//
// Classes (embed these in an application; the main() below is the demo
// driver the tests run):
//   msgpk::Writer / msgpk::Value  — minimal msgpack codec (subset)
//   RpcClient                     — blocking control-plane RPC
//   StoreClient                   — object create/seal/get via shm
//   RayTpuClient                  — Init / Put / Get / Submit / Kv*
//
// Build: g++ -O2 -std=c++17 -pthread -o frontend frontend.cpp -lrt

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

#include "util.hpp"  // exponential backoff for connect retries (N18)

namespace msgpk {

// ---------------------------------------------------------------------------
// Writer: canonical wide encodings (the Python msgpack lib accepts any
// well-formed width, so fixed-width keeps the encoder tiny).
// ---------------------------------------------------------------------------

struct Writer {
  std::string out;

  void nil() { out.push_back((char)0xc0); }
  void boolean(bool b) { out.push_back((char)(b ? 0xc3 : 0xc2)); }
  void i64(int64_t v) {
    out.push_back((char)0xd3);
    be64((uint64_t)v);
  }
  void f64(double v) {
    out.push_back((char)0xcb);
    uint64_t bits;
    memcpy(&bits, &v, 8);
    be64(bits);
  }
  void str(const std::string &s) {
    out.push_back((char)0xdb);
    be32((uint32_t)s.size());
    out += s;
  }
  void bin(const std::string &s) {
    out.push_back((char)0xc6);
    be32((uint32_t)s.size());
    out += s;
  }
  void array(uint32_t n) {
    out.push_back((char)0xdd);
    be32(n);
  }
  void map(uint32_t n) {
    out.push_back((char)0xdf);
    be32(n);
  }

 private:
  void be32(uint32_t v) {
    for (int i = 3; i >= 0; --i) out.push_back((char)((v >> (8 * i)) & 0xff));
  }
  void be64(uint64_t v) {
    for (int i = 7; i >= 0; --i) out.push_back((char)((v >> (8 * i)) & 0xff));
  }
};

// ---------------------------------------------------------------------------
// Value + parser (subset: everything the control plane emits)
// ---------------------------------------------------------------------------

struct Value {
  enum Type { NIL, BOOL, INT, FLOAT, STR, BIN, ARR, MAP } type = NIL;
  bool b = false;
  int64_t i = 0;
  double d = 0;
  std::string s;  // STR and BIN payloads
  std::vector<Value> arr;
  std::vector<std::pair<Value, Value>> map;

  const Value *get(const std::string &key) const {
    for (auto &kv : map)
      if (kv.first.type == STR && kv.first.s == key) return &kv.second;
    return nullptr;
  }
  bool truthy() const {
    switch (type) {
      case BOOL: return b;
      case INT: return i != 0;
      case NIL: return false;
      default: return true;
    }
  }
};

struct Parser {
  const uint8_t *p, *end;
  explicit Parser(const std::string &buf)
      : p((const uint8_t *)buf.data()), end(p + buf.size()) {}

  Value parse() {
    need(1);
    uint8_t t = *p++;
    Value v;
    if (t <= 0x7f) {  // positive fixint
      v.type = Value::INT; v.i = t; return v;
    }
    if (t >= 0xe0) {  // negative fixint
      v.type = Value::INT; v.i = (int8_t)t; return v;
    }
    if ((t & 0xf0) == 0x80) return map_body(t & 0x0f);
    if ((t & 0xf0) == 0x90) return arr_body(t & 0x0f);
    if ((t & 0xe0) == 0xa0) return str_body(t & 0x1f);
    switch (t) {
      case 0xc0: return v;
      case 0xc2: v.type = Value::BOOL; v.b = false; return v;
      case 0xc3: v.type = Value::BOOL; v.b = true; return v;
      case 0xc4: return bin_body(u(1));
      case 0xc5: return bin_body(u(2));
      case 0xc6: return bin_body(u(4));
      case 0xca: {
        uint32_t bits = (uint32_t)u(4); float f; memcpy(&f, &bits, 4);
        v.type = Value::FLOAT; v.d = f; return v;
      }
      case 0xcb: {
        uint64_t bits = u(8); double dd; memcpy(&dd, &bits, 8);
        v.type = Value::FLOAT; v.d = dd; return v;
      }
      case 0xcc: v.type = Value::INT; v.i = (int64_t)u(1); return v;
      case 0xcd: v.type = Value::INT; v.i = (int64_t)u(2); return v;
      case 0xce: v.type = Value::INT; v.i = (int64_t)u(4); return v;
      case 0xcf: v.type = Value::INT; v.i = (int64_t)u(8); return v;
      case 0xd0: v.type = Value::INT; v.i = (int8_t)u(1); return v;
      case 0xd1: v.type = Value::INT; v.i = (int16_t)u(2); return v;
      case 0xd2: v.type = Value::INT; v.i = (int32_t)u(4); return v;
      case 0xd3: v.type = Value::INT; v.i = (int64_t)u(8); return v;
      case 0xd9: return str_body(u(1));
      case 0xda: return str_body(u(2));
      case 0xdb: return str_body(u(4));
      case 0xdc: return arr_body(u(2));
      case 0xdd: return arr_body(u(4));
      case 0xde: return map_body(u(2));
      case 0xdf: return map_body(u(4));
      default: throw std::runtime_error("msgpack: unsupported tag");
    }
  }

 private:
  void need(size_t n) {
    if ((size_t)(end - p) < n) throw std::runtime_error("msgpack: truncated");
  }
  uint64_t u(int nbytes) {
    need(nbytes);
    uint64_t v = 0;
    for (int i = 0; i < nbytes; ++i) v = (v << 8) | *p++;
    return v;
  }
  Value str_body(uint64_t n) {
    need(n);
    Value v; v.type = Value::STR; v.s.assign((const char *)p, n); p += n;
    return v;
  }
  Value bin_body(uint64_t n) {
    need(n);
    Value v; v.type = Value::BIN; v.s.assign((const char *)p, n); p += n;
    return v;
  }
  Value arr_body(uint64_t n) {
    Value v; v.type = Value::ARR;
    for (uint64_t i = 0; i < n; ++i) v.arr.push_back(parse());
    return v;
  }
  Value map_body(uint64_t n) {
    Value v; v.type = Value::MAP;
    for (uint64_t i = 0; i < n; ++i) {
      Value k = parse();
      v.map.emplace_back(std::move(k), parse());
    }
    return v;
  }
};

}  // namespace msgpk

// ---------------------------------------------------------------------------
// socket helpers
// ---------------------------------------------------------------------------

static bool WriteExact(int fd, const void *buf, size_t n) {
  const char *b = (const char *)buf;
  while (n) {
    ssize_t w = write(fd, b, n);
    if (w <= 0) return false;
    b += w; n -= w;
  }
  return true;
}

static bool ReadExact(int fd, void *buf, size_t n) {
  char *b = (char *)buf;
  while (n) {
    ssize_t r = read(fd, b, n);
    if (r <= 0) return false;
    b += r; n -= r;
  }
  return true;
}

// ---------------------------------------------------------------------------
// RpcClient — the _private/rpc.py wire format ([u32 len][msgpack array])
// ---------------------------------------------------------------------------

class RpcClient {
 public:
  explicit RpcClient(const std::string &address) {
    auto colon = address.rfind(':');
    std::string host = address.substr(0, colon);
    std::string port = address.substr(colon + 1);
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0 || !res)
      throw std::runtime_error("resolve failed: " + address);
    // retry with exponential backoff: the head's services come up in
    // order, and a frontend launched alongside them must not race the
    // listener into a hard failure (reference: client reconnect backoff)
    rt_util::ExponentialBackoff backoff(20, 2.0, 500);
    bool connected = false;
    for (int attempt = 0; attempt < 6; ++attempt) {
      fd_ = socket(res->ai_family, res->ai_socktype, 0);
      if (fd_ >= 0 && connect(fd_, res->ai_addr, res->ai_addrlen) == 0) {
        connected = true;
        break;
      }
      if (fd_ >= 0) close(fd_);
      fd_ = -1;
      if (attempt < 5) usleep((useconds_t)(backoff.Next() * 1000));
    }
    freeaddrinfo(res);
    if (!connected) throw std::runtime_error("connect failed: " + address);
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, 1 /*TCP_NODELAY*/, &one, sizeof(one));
    Handshake();
  }
  ~RpcClient() {
    if (fd_ >= 0) close(fd_);
  }

  // payload_msgpack: pre-encoded msgpack for the payload slot
  msgpk::Value Call(const std::string &method,
                    const std::string &payload_msgpack) {
    uint64_t id = ++msgid_;
    msgpk::Writer w;
    w.array(4);
    w.i64(0);  // REQUEST
    w.i64((int64_t)id);
    w.str(method);
    w.out += payload_msgpack;
    SendFrame(w.out);
    for (;;) {
      msgpk::Value msg = ReadFrame();
      if (msg.arr.size() != 4) continue;
      int64_t mtype = msg.arr[0].i;
      if (mtype != 1) continue;  // skip NOTIFY pushes
      if ((uint64_t)msg.arr[1].i != id) continue;
      if (!msg.arr[2].truthy())
        throw std::runtime_error("rpc " + method + " failed: " +
                                 msg.arr[3].s.substr(0, 400));
      return std::move(msg.arr[3]);
    }
  }

 private:
  void Handshake() {
    // schema.py handshake_payload(): {"protocol": N, "version": "..."}
    msgpk::Writer p;
    p.map(2);
    p.str("protocol");
    p.i64(1);  // PROTOCOL_VERSION (schema.py) — bump together
    p.str("version");
    p.str("cpp-frontend");
    Call("_handshake", p.out);
  }
  void SendFrame(const std::string &body) {
    uint32_t len = (uint32_t)body.size();  // little-endian, matches rpc.py
    char hdr[4];
    memcpy(hdr, &len, 4);
    if (!WriteExact(fd_, hdr, 4) || !WriteExact(fd_, body.data(), body.size()))
      throw std::runtime_error("rpc send failed");
  }
  msgpk::Value ReadFrame() {
    char hdr[4];
    if (!ReadExact(fd_, hdr, 4)) throw std::runtime_error("rpc recv failed");
    uint32_t len;
    memcpy(&len, hdr, 4);
    std::string body(len, '\0');
    if (!ReadExact(fd_, body.data(), len))
      throw std::runtime_error("rpc recv failed");
    msgpk::Parser parser(body);
    return parser.parse();
  }

  int fd_ = -1;
  uint64_t msgid_ = 0;
};

// ---------------------------------------------------------------------------
// StoreClient — cpp/store.cpp unix-socket protocol
// ---------------------------------------------------------------------------

class StoreClient {
 public:
  static constexpr size_t kIdSize = 28;

  explicit StoreClient(const std::string &socket_path) {
    fd_ = socket(AF_UNIX, SOCK_STREAM, 0);
    sockaddr_un addr{};
    addr.sun_family = AF_UNIX;
    strncpy(addr.sun_path, socket_path.c_str(), sizeof(addr.sun_path) - 1);
    if (fd_ < 0 || connect(fd_, (sockaddr *)&addr, sizeof(addr)) != 0)
      throw std::runtime_error("store connect failed: " + socket_path);
  }
  ~StoreClient() {
    if (fd_ >= 0) close(fd_);
  }

  // Put: CREATE + memcpy into the shm mapping + SEAL.
  void Put(const std::string &id, const std::string &payload) {
    std::string req;
    uint64_t size = payload.size();
    req.append((const char *)&size, 8);
    auto resp = Op(1 /*CREATE*/, id, req);
    if (resp.first != 0)
      throw std::runtime_error("store create failed, status " +
                               std::to_string(resp.first));
    const std::string &shm_name = resp.second;
    int sfd = shm_open(shm_name.c_str(), O_RDWR, 0600);
    if (sfd < 0) throw std::runtime_error("shm_open failed: " + shm_name);
    if (size) {
      void *m = mmap(nullptr, size, PROT_WRITE, MAP_SHARED, sfd, 0);
      close(sfd);
      if (m == MAP_FAILED) throw std::runtime_error("mmap failed");
      memcpy(m, payload.data(), size);
      munmap(m, size);
    } else {
      close(sfd);
    }
    auto seal = Op(2 /*SEAL*/, id, std::string(1, '\0'));  // pin=false
    if (seal.first != 0)
      throw std::runtime_error("store seal failed");
  }

  // Get: blocks in the daemon until sealed or timeout.
  std::string Get(const std::string &id, uint64_t timeout_ms) {
    std::string req((const char *)&timeout_ms, 8);
    auto resp = Op(3 /*GET*/, id, req);
    if (resp.first == 4) throw std::runtime_error("store get timeout");
    if (resp.first != 0)
      throw std::runtime_error("store get failed, status " +
                               std::to_string(resp.first));
    uint64_t size;
    memcpy(&size, resp.second.data(), 8);
    std::string shm_name = resp.second.substr(8);
    std::string out;
    if (size) {
      int sfd = shm_open(shm_name.c_str(), O_RDONLY, 0600);
      if (sfd < 0) throw std::runtime_error("shm_open failed: " + shm_name);
      void *m = mmap(nullptr, size, PROT_READ, MAP_SHARED, sfd, 0);
      close(sfd);
      if (m == MAP_FAILED) throw std::runtime_error("mmap failed");
      out.assign((const char *)m, size);
      munmap(m, size);
    }
    Op(4 /*RELEASE*/, id, "");
    return out;
  }

  bool Contains(const std::string &id) {
    return Op(6 /*CONTAINS*/, id, "").first == 0;
  }

 private:
  std::pair<uint8_t, std::string> Op(uint8_t op, const std::string &id,
                                     const std::string &payload) {
    if (id.size() != kIdSize) throw std::runtime_error("bad object id size");
    uint32_t len = (uint32_t)(1 + kIdSize + payload.size());
    std::string req;
    req.append((const char *)&len, 4);
    req.push_back((char)op);
    req += id;
    req += payload;
    if (!WriteExact(fd_, req.data(), req.size()))
      throw std::runtime_error("store send failed");
    char hdr[4];
    if (!ReadExact(fd_, hdr, 4)) throw std::runtime_error("store recv failed");
    uint32_t rlen;
    memcpy(&rlen, hdr, 4);
    std::string body(rlen, '\0');
    if (rlen && !ReadExact(fd_, body.data(), rlen))
      throw std::runtime_error("store recv failed");
    uint8_t status = (uint8_t)body[0];
    return {status, body.substr(1)};
  }

  int fd_ = -1;
};

// ---------------------------------------------------------------------------
// RayTpuClient — the frontend API
// ---------------------------------------------------------------------------

class RayTpuClient {
 public:
  RayTpuClient(const std::string &gcs_address, const std::string &store_socket)
      : gcs_(gcs_address), store_(store_socket) {
    // job + driver-task identity (ids.py: JobID 4B; driver TaskID =
    // 20 zero bytes + job)
    msgpk::Writer empty;
    empty.nil();
    auto r = gcs_.Call("next_job_id", empty.out);
    const msgpk::Value *jid = r.get("job_id");
    if (!jid) throw std::runtime_error("next_job_id: no job_id");
    job_id_ = jid->s;
    driver_task_ = std::string(20, '\0') + job_id_;
    // raylet address from the node table
    auto nodes = gcs_.Call("get_nodes", empty.out);
    const msgpk::Value *arr = nodes.get("nodes");
    if (!arr || arr->arr.empty())
      throw std::runtime_error("no nodes registered");
    const msgpk::Value *addr = arr->arr[0].get("address");
    raylet_ = std::make_unique<RpcClient>(addr->s);
  }

  // -- kv --
  void KvPut(const std::string &key, const std::string &value) {
    msgpk::Writer p;
    p.map(2);
    p.str("key"); p.bin(key);
    p.str("value"); p.bin(value);
    gcs_.Call("kv_put", p.out);
  }
  std::string KvGet(const std::string &key) {
    msgpk::Writer p;
    p.map(1);
    p.str("key"); p.bin(key);
    auto r = gcs_.Call("kv_get", p.out);
    const msgpk::Value *v = r.get("value");
    return v ? v->s : "";
  }

  size_t NumNodes() {
    msgpk::Writer empty;
    empty.nil();
    auto nodes = gcs_.Call("get_nodes", empty.out);
    return nodes.get("nodes")->arr.size();
  }

  // -- objects (XLANG envelope: [u32 0xFFFFFFFF][u64 len][msgpack]) --
  std::string Put(const std::string &value_msgpack) {
    std::string id = NextObjectId(true);
    store_.Put(id, XlangEnvelope(value_msgpack));
    return id;
  }

  msgpk::Value Get(const std::string &id, uint64_t timeout_ms) {
    std::string payload = store_.Get(id, timeout_ms);
    if (payload.size() < 12) throw std::runtime_error("short object");
    uint32_t nbuf;
    memcpy(&nbuf, payload.data(), 4);
    if (nbuf != 0xFFFFFFFFu)
      throw std::runtime_error(
          "object is not cross-language (pickled by a Python worker without "
          "xlang=true)");
    uint64_t len;
    memcpy(&len, payload.data() + 4, 8);
    std::string body = payload.substr(12, len);
    msgpk::Parser parser(body);
    return parser.parse();
  }

  // -- tasks: function descriptor + msgpack args; returns the result oid --
  std::string Submit(const std::string &func_desc,
                     const std::string &args_msgpack_array,
                     double num_cpus = 1.0) {
    std::string task_id = RandomBytes(20) + job_id_;  // TaskID.for_task
    // args_blob = XLANG msgpack of [args, kwargs]
    msgpk::Writer args;
    args.array(2);
    args.out += args_msgpack_array;
    args.map(0);  // kwargs
    msgpk::Writer spec;
    spec.map(22);
    spec.str("task_id"); spec.bin(task_id);
    spec.str("job_id"); spec.bin(job_id_);
    spec.str("name"); spec.str(func_desc);
    spec.str("type"); spec.str("normal");
    spec.str("function_blob"); spec.nil();
    spec.str("function_desc"); spec.str(func_desc);
    spec.str("function_id"); spec.bin(func_desc);  // cache key
    spec.str("method_name"); spec.nil();
    spec.str("args_blob"); spec.bin(XlangEnvelope(args.out));
    spec.str("arg_deps"); spec.array(0);
    spec.str("num_returns"); spec.i64(1);
    spec.str("streaming"); spec.boolean(false);
    spec.str("resources");
    spec.map(1); spec.str("CPU"); spec.f64(num_cpus);
    spec.str("actor_id"); spec.nil();
    spec.str("seqno"); spec.i64(0);
    spec.str("max_retries"); spec.i64(0);
    spec.str("retry_count"); spec.i64(0);
    spec.str("placement"); spec.nil();
    spec.str("scheduling");
    spec.map(1); spec.str("type"); spec.str("default");
    spec.str("runtime_env"); spec.nil();
    spec.str("xlang"); spec.boolean(true);  // msgpack returns
    spec.str("owner_address"); spec.str("");
    msgpk::Writer p;
    p.map(1);
    p.str("spec");
    p.out += spec.out;
    auto r = raylet_->Call("submit_task", p.out);
    if (!r.get("ok") || !r.get("ok")->truthy())
      throw std::runtime_error("submit_task rejected");
    return task_id + std::string("\x00\x00\x00\x00", 4);  // return index 0
  }

 private:
  static std::string RandomBytes(size_t n) {
    std::string out(n, '\0');
    FILE *f = fopen("/dev/urandom", "rb");
    if (!f || fread(out.data(), 1, n, f) != n)
      throw std::runtime_error("urandom failed");
    fclose(f);
    return out;
  }
  std::string NextObjectId(bool is_put) {
    uint32_t idx = ++put_index_;
    if (is_put) idx |= 0x80000000u;  // ObjectID.PUT_BIT
    std::string id = driver_task_;
    id.append((const char *)&idx, 4);  // little-endian
    return id;
  }
  static std::string XlangEnvelope(const std::string &msgpack_bytes) {
    std::string out;
    uint32_t sentinel = 0xFFFFFFFFu;
    uint64_t len = msgpack_bytes.size();
    out.append((const char *)&sentinel, 4);
    out.append((const char *)&len, 8);
    out += msgpack_bytes;
    return out;
  }

  RpcClient gcs_;
  StoreClient store_;
  std::unique_ptr<RpcClient> raylet_;
  std::string job_id_, driver_task_;
  uint32_t put_index_ = 0;
};

// ---------------------------------------------------------------------------
// demo driver (what tests/test_cpp_frontend.py runs)
// ---------------------------------------------------------------------------

int main(int argc, char **argv) {
  if (argc < 4) {
    fprintf(stderr,
            "usage: %s <gcs_addr> <store_sock> <kv|putget|submit> [args]\n",
            argv[0]);
    return 2;
  }
  try {
    RayTpuClient client(argv[1], argv[2]);
    std::string cmd = argv[3];
    if (cmd == "kv") {
      client.KvPut("cpp_key", "cpp_value");
      printf("kv:%s\n", client.KvGet("cpp_key").c_str());
      printf("nodes:%zu\n", client.NumNodes());
      return 0;
    }
    if (cmd == "putget") {
      msgpk::Writer v;
      v.map(2);
      v.str("msg"); v.str("hello from c++");
      v.str("n"); v.i64(1234);
      std::string oid = client.Put(v.out);
      msgpk::Value back = client.Get(oid, 10000);
      printf("putget:%s:%lld\n", back.get("msg")->s.c_str(),
             (long long)back.get("n")->i);
      // print the oid hex so Python can fetch the same object
      for (unsigned char c : oid) printf("%02x", c);
      printf("\n");
      return 0;
    }
    if (cmd == "submit") {
      // submit <module:callable> <int> <int> — two integer args
      msgpk::Writer args;
      args.array(2);
      args.i64(atoll(argv[5]));
      args.i64(atoll(argv[6]));
      std::string oid = client.Submit(argv[4], args.out);
      msgpk::Value result = client.Get(oid, 60000);
      if (result.type == msgpk::Value::FLOAT)
        printf("result:%.6f\n", result.d);
      else
        printf("result:%lld\n", (long long)result.i);
      return 0;
    }
    fprintf(stderr, "unknown command %s\n", cmd.c_str());
    return 2;
  } catch (const std::exception &e) {
    fprintf(stderr, "error: %s\n", e.what());
    return 1;
  }
}
