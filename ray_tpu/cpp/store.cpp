// Shared-memory immutable object store daemon ("plasma equivalent").
//
// TPU-native rebuild of the reference's plasma store
// (reference: src/ray/object_manager/plasma/store.h:55 — shared-memory
// immutable object store embedded in the raylet; dlmalloc arena over mmap,
// ObjectLifecycleManager + LRU EvictionPolicy, create/get queues, client
// over unix socket). Design differences, deliberate for the TPU host path:
//
//  * One POSIX shm segment **per object** (shm_open) instead of one dlmalloc
//    arena: clients mmap exactly the object they touch, the kernel reclaims
//    a segment the moment its refcount drops to zero and it is unlinked, and
//    host buffers handed to jax.device_put are page-aligned by construction.
//  * Thread-per-connection unix-socket server (host object churn is a
//    control-plane rate, not a data-plane rate — data moves via mmap).
//  * LRU eviction of sealed, unreferenced objects when a create would exceed
//    the byte budget (reference: plasma/eviction_policy.h:199).
//
// Wire protocol (little-endian u32 framing), one request per message:
//   req:  [u32 len][u8 op][28B object_id][payload]
//   resp: [u32 len][u8 status][payload]
// ops: 1=CREATE(u64 size) -> shm name; 2=SEAL; 3=GET(u64 timeout_ms) ->
//      shm name+size; 4=RELEASE; 5=DELETE; 6=CONTAINS; 7=LIST; 8=STATS;
//      9=SHUTDOWN; 10=SUBSCRIBE (connection becomes a push-only event
//      stream: [u32 len][u8 event][28B id], event 1=SEALED 2=EVICTED —
//      the plasma→raylet notification socket analog, feeding the object
//      directory); 11=ABORT (drop an unsealed create, e.g. failed pull);
//      12=PIN / 13=UNPIN (long-lived reference by the raylet for primary
//      copies — pinned objects are never LRU-evicted, only spilled);
//      14=WAIT (payload: u64 timeout_ms, u32 k, u32 n, n*28B ids → reply
//      u32 m + m*28B ids that are present, blocking until >=k or timeout —
//      the native replacement for client-side contains() busy-polling).
// status: 0=OK 1=NOT_FOUND 2=EXISTS 3=FULL 4=TIMEOUT 5=ERR 6=EVICTED
//
// Spilling (reference: raylet/local_object_manager.cc spill/restore +
// external_storage.py — here implemented natively inside the daemon):
// under memory pressure, unreferenced sealed objects are LRU-EVICTED
// (recoverable via lineage); referenced/pinned sealed objects are SPILLED
// to <spill_dir> and transparently restored into fresh shm on the next
// Get. argv: <socket> <capacity> [spill_dir] — no spill_dir disables
// spilling (pressure then fails creates with FULL, as before).
//
// Build: g++ -O2 -std=c++17 -pthread -o ray_tpu_store store.cpp -lrt

#include <fcntl.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <list>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "util.hpp"  // structured events / throttling / counters (N18)

namespace {
rt_util::CounterMap g_counters;           // lifetime op counters
rt_util::Throttler g_pressure_log(1000);  // >=1s between pressure events
}  // namespace

namespace {

constexpr uint8_t OP_CREATE = 1, OP_SEAL = 2, OP_GET = 3, OP_RELEASE = 4,
                  OP_DELETE = 5, OP_CONTAINS = 6, OP_LIST = 7, OP_STATS = 8,
                  OP_SHUTDOWN = 9, OP_SUBSCRIBE = 10, OP_ABORT = 11,
                  OP_PIN = 12, OP_UNPIN = 13, OP_WAIT = 14;
constexpr uint8_t ST_OK = 0, ST_NOT_FOUND = 1, ST_EXISTS = 2, ST_FULL = 3,
                  ST_TIMEOUT = 4, ST_ERR = 5, ST_EVICTED = 6;
constexpr uint8_t EV_SEALED = 1, EV_EVICTED = 2;
constexpr size_t ID_SIZE = 28;

bool WriteExact(int fd, const void *buf, size_t n);
bool ReadExact(int fd, void *buf, size_t n);

struct ObjectEntry {
  std::string shm_name;
  uint64_t size = 0;
  bool sealed = false;
  int64_t refcount = 0;  // client references; creator holds one until seal
  uint64_t lru_tick = 0;
  bool spilled = false;      // bytes live in spill_path, not in shm
  std::string spill_path;
};

class Store {
 public:
  Store(uint64_t capacity, std::string spill_dir, uint64_t min_spill)
      : capacity_(capacity), spill_dir_(std::move(spill_dir)),
        min_spill_(min_spill) {}

  uint8_t Create(const std::string &id, uint64_t size, std::string *shm_name) {
    std::unique_lock<std::mutex> lk(mu_);
    if (closing_) return ST_ERR;  // shutting down: no new segments may appear
    if (objects_.count(id)) return ST_EXISTS;
    tombstones_.erase(id);  // reconstruction recreates an evicted object
    if (!EnsureCapacityLocked(size)) return ST_FULL;
    std::string name = "/rt_store_" + std::to_string(getpid()) + "_" +
                       Hex(id.substr(0, 8)) + "_" + std::to_string(seq_++);
    int fd = shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return ST_ERR;
    if (ftruncate(fd, (off_t)size) != 0) {
      close(fd);
      shm_unlink(name.c_str());
      return ST_FULL;
    }
    close(fd);
    ObjectEntry e;
    e.shm_name = name;
    e.size = size;
    e.refcount = 1;  // creator's reference until Seal
    objects_[id] = e;
    used_ += size;
    *shm_name = name;
    return ST_OK;
  }

  // Abort an unsealed create (creator died before seal): remove without
  // tombstoning so a retry's create() succeeds cleanly.
  void Abort(const std::string &id) {
    std::unique_lock<std::mutex> lk(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end() || it->second.sealed) return;
    shm_unlink(it->second.shm_name.c_str());
    used_ -= it->second.size;
    objects_.erase(it);
  }

  // pin=true converts the creator's reference into a long-lived pin
  // ATOMICALLY with the seal — primary copies must never be evictable in
  // the window before the raylet's async pin would land.
  uint8_t Seal(const std::string &id, bool pin) {
    std::unique_lock<std::mutex> lk(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return ST_NOT_FOUND;
    it->second.sealed = true;
    if (!pin) it->second.refcount--;  // drop creator ref; LRU-evictable at 0
    it->second.lru_tick = tick_++;
    PushEventLocked(EV_SEALED, id);
    sealed_cv_.notify_all();
    return ST_OK;
  }

  uint8_t Get(const std::string &id, uint64_t timeout_ms, std::string *shm_name,
              uint64_t *size) {
    std::unique_lock<std::mutex> lk(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    for (;;) {
      auto it = objects_.find(id);
      if (it != objects_.end() && it->second.sealed) {
        if (it->second.spilled && !RestoreLocked(id, it->second)) return ST_ERR;
        it->second.refcount++;
        it->second.lru_tick = tick_++;
        *shm_name = it->second.shm_name;
        *size = it->second.size;
        return ST_OK;
      }
      // Evicted objects report distinctly so owners can trigger lineage
      // reconstruction (reference: ObjectRecoveryManager,
      // core_worker/object_recovery_manager.h:41).
      if (it == objects_.end() && tombstones_.count(id)) return ST_EVICTED;
      if (timeout_ms == 0) return ST_NOT_FOUND;
      if (sealed_cv_.wait_until(lk, deadline) == std::cv_status::timeout)
        return ST_TIMEOUT;
    }
  }

  uint8_t Release(const std::string &id) {
    std::unique_lock<std::mutex> lk(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) return ST_NOT_FOUND;
    if (it->second.refcount > 0) it->second.refcount--;
    return ST_OK;
  }

  // Long-lived reference for primary copies (raylet-held); pinned objects
  // are never LRU-evicted — under pressure they spill instead.
  uint8_t Pin(const std::string &id) {
    std::unique_lock<std::mutex> lk(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end() || !it->second.sealed) return ST_NOT_FOUND;
    it->second.refcount++;
    return ST_OK;
  }

  uint8_t Unpin(const std::string &id) { return Release(id); }

  // Block until >= k of `ids` are present (sealed, in memory or spilled)
  // or the deadline passes; returns the present subset. The seal cv wakes
  // every waiter, so one daemon serves many concurrent wait() calls
  // without any client-side polling.
  std::vector<std::string> WaitAny(const std::vector<std::string> &ids,
                                   size_t k, uint64_t timeout_ms) {
    std::unique_lock<std::mutex> lk(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms);
    bool timed_out = false;
    for (;;) {
      std::vector<std::string> present;
      for (const auto &id : ids) {
        auto it = objects_.find(id);
        if (it != objects_.end() && it->second.sealed) present.push_back(id);
      }
      if (present.size() >= k || timeout_ms == 0 || timed_out) return present;
      timed_out =
          sealed_cv_.wait_until(lk, deadline) == std::cv_status::timeout;
    }
  }

  uint8_t Delete(const std::string &id) {
    std::unique_lock<std::mutex> lk(mu_);
    auto it = objects_.find(id);
    if (it == objects_.end()) {
      // Delete is idempotent AND final even for ids never created here:
      // tombstoning unknown ids lets sweepers retire object ids whose
      // producer died before sealing (KV-handoff leak sweep), and the
      // wakeup below bounces any getter blocked on that id immediately
      // (ST_EVICTED) instead of letting it sleep out its full timeout.
      tombstones_.insert(id);
      sealed_cv_.notify_all();
      return ST_NOT_FOUND;
    }
    // Unlink now; clients holding an mmap keep their pages until they unmap.
    if (it->second.spilled) {
      unlink(it->second.spill_path.c_str());
    } else {
      shm_unlink(it->second.shm_name.c_str());
      used_ -= it->second.size;
    }
    objects_.erase(it);
    tombstones_.insert(id);
    PushEventLocked(EV_EVICTED, id);
    // Wake blocked getters so a get racing this delete surfaces
    // ST_EVICTED promptly rather than hanging until its deadline.
    sealed_cv_.notify_all();
    return ST_OK;
  }

  uint8_t Contains(const std::string &id) {
    std::unique_lock<std::mutex> lk(mu_);
    auto it = objects_.find(id);
    if (it != objects_.end() && it->second.sealed) return ST_OK;
    if (it == objects_.end() && tombstones_.count(id)) return ST_EVICTED;
    return ST_NOT_FOUND;
  }

  std::vector<std::string> List() {
    std::unique_lock<std::mutex> lk(mu_);
    std::vector<std::string> out;
    for (auto &kv : objects_)
      if (kv.second.sealed) out.push_back(kv.first);
    return out;
  }

  void Stats(uint64_t *used, uint64_t *capacity, uint64_t *count) {
    std::unique_lock<std::mutex> lk(mu_);
    *used = used_;
    *capacity = capacity_;
    *count = objects_.size();
  }

  // Final cleanup: gate new creates first, then unlink every segment. A
  // create in flight when we take mu_ has already inserted its entry, so
  // it gets unlinked here; creates arriving after see closing_ and fail.
  void UnlinkAll() {
    std::unique_lock<std::mutex> lk(mu_);
    closing_ = true;
    for (auto &kv : objects_) {
      if (kv.second.spilled)
        unlink(kv.second.spill_path.c_str());
      else
        shm_unlink(kv.second.shm_name.c_str());
    }
    objects_.clear();
    used_ = 0;
  }

  // -- event notification stream (plasma notification socket analog) --

  // Registers the fd and sends the subscribe ACK under subs_mu_, so the
  // ACK is ordered before any event the notifier writes to this fd and no
  // seal after the client observes the ACK can be missed.
  void Subscribe(int fd) {
    std::unique_lock<std::mutex> lk(subs_mu_);
    uint32_t len = 1;
    std::string msg;
    msg.append((char *)&len, 4);
    msg.push_back((char)0 /* ST_OK */);
    WriteExact(fd, msg.data(), msg.size());
    sub_fds_.push_back(fd);
  }

  void StartNotifier() {
    notifier_ = std::thread([this] { NotifierLoop(); });
  }

  void StopNotifier() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stopping_ = true;
      events_cv_.notify_all();
    }
    if (notifier_.joinable()) notifier_.join();
    std::unique_lock<std::mutex> lk(subs_mu_);
    for (int fd : sub_fds_) close(fd);
    sub_fds_.clear();
  }

 private:
  // Caller holds mu_. Events drain on a dedicated thread so a slow
  // subscriber never blocks store operations.
  void PushEventLocked(uint8_t ev, const std::string &id) {
    std::string frame;
    uint32_t len = 1 + (uint32_t)ID_SIZE;
    frame.append((char *)&len, 4);
    frame.push_back((char)ev);
    frame.append(id);
    events_.push_back(std::move(frame));
    events_cv_.notify_one();
  }

  void NotifierLoop() {
    for (;;) {
      std::deque<std::string> batch;
      {
        std::unique_lock<std::mutex> lk(mu_);
        events_cv_.wait(lk, [&] { return !events_.empty() || stopping_; });
        if (stopping_ && events_.empty()) return;
        batch.swap(events_);
      }
      std::unique_lock<std::mutex> slk(subs_mu_);
      for (auto it = sub_fds_.begin(); it != sub_fds_.end();) {
        bool ok = true;
        for (auto &f : batch) {
          if (!WriteExact(*it, f.data(), f.size())) {
            ok = false;
            break;
          }
        }
        if (!ok) {
          close(*it);
          it = sub_fds_.erase(it);
        } else {
          ++it;
        }
      }
    }
  }

  // Make room for `needed` bytes. Caller holds mu_. Policy (reference:
  // eviction_policy.h LRU + local_object_manager.cc spill): first LRU-EVICT
  // sealed, unreferenced, in-memory objects (recoverable via lineage or
  // other copies); then SPILL referenced/pinned sealed objects to disk
  // (restored on Get, never lost). IO runs under mu_ — a deliberate v1
  // simplification; object churn is control-plane rate here.
  bool EnsureCapacityLocked(uint64_t needed) {
    while (used_ + needed > capacity_) {
      std::string victim;
      uint64_t best_tick = UINT64_MAX;
      for (auto &kv : objects_) {
        if (kv.second.sealed && !kv.second.spilled && kv.second.refcount == 0 &&
            kv.second.size > 0 && kv.second.lru_tick < best_tick) {
          best_tick = kv.second.lru_tick;
          victim = kv.first;
        }
      }
      if (!victim.empty()) {
        auto it = objects_.find(victim);
        shm_unlink(it->second.shm_name.c_str());
        used_ -= it->second.size;
        objects_.erase(it);
        tombstones_.insert(victim);
        PushEventLocked(EV_EVICTED, victim);
        // getters blocked on the victim learn ST_EVICTED now, not at
        // their deadline (same contract as Delete)
        sealed_cv_.notify_all();
        g_counters.Inc("objects_evicted");
        if (g_pressure_log.AbleToRun()) {
          rt_util::Event("INFO", "store_lru_eviction",
                         "\"used_bytes\":" + std::to_string(used_));
        }
        continue;
      }
      if (spill_dir_.empty()) return false;
      // no evictable object: spill referenced in-memory objects, LRU
      // first, as a BATCH of at least min_spill_ bytes per pass so disk
      // IO is amortized (reference: local_object_manager.cc spills in
      // >= min_spilling_size batches)
      std::vector<std::pair<uint64_t, std::string>> order;
      for (auto &kv : objects_) {
        if (kv.second.sealed && !kv.second.spilled && kv.second.size > 0)
          order.emplace_back(kv.second.lru_tick, kv.first);
      }
      if (order.empty()) return false;
      std::sort(order.begin(), order.end());
      uint64_t want = needed > min_spill_ ? needed : min_spill_;
      uint64_t freed = 0;
      bool any = false;
      for (auto &tick_id : order) {
        if (freed >= want) break;
        ObjectEntry &e = objects_[tick_id.second];
        uint64_t sz = e.size;
        if (SpillLocked(tick_id.second, e)) {
          freed += sz;
          any = true;
        }
      }
      if (!any) return false;
    }
    return true;
  }

  bool SpillLocked(const std::string &id, ObjectEntry &e) {
    std::string path = spill_dir_ + "/" + Hex(id);
    int sfd = shm_open(e.shm_name.c_str(), O_RDONLY, 0600);
    if (sfd < 0) return false;
    void *src = nullptr;
    if (e.size > 0) {
      src = mmap(nullptr, e.size, PROT_READ, MAP_SHARED, sfd, 0);
      close(sfd);
      if (src == MAP_FAILED) return false;
    } else {
      close(sfd);
    }
    int out = open(path.c_str(), O_CREAT | O_TRUNC | O_WRONLY, 0600);
    if (out < 0) {
      if (src) munmap(src, e.size);
      return false;
    }
    bool ok = e.size == 0 || WriteExact(out, src, e.size);
    close(out);
    if (src) munmap(src, e.size);
    if (!ok) {
      unlink(path.c_str());
      return false;
    }
    shm_unlink(e.shm_name.c_str());
    e.spilled = true;
    e.spill_path = path;
    used_ -= e.size;
    g_counters.Inc("objects_spilled");
    g_counters.Inc("bytes_spilled", e.size);
    if (g_pressure_log.AbleToRun()) {
      rt_util::Event("INFO", "store_spill",
                     "\"bytes\":" + std::to_string(e.size) +
                     ",\"used_bytes\":" + std::to_string(used_));
    }
    return true;
  }

  bool RestoreLocked(const std::string &id, ObjectEntry &e) {
    if (closing_) return false;  // no new segments after UnlinkAll
    if (!EnsureCapacityLocked(e.size)) return false;
    std::string name = "/rt_store_" + std::to_string(getpid()) + "_" +
                       Hex(id.substr(0, 8)) + "_" + std::to_string(seq_++);
    int fd = shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) return false;
    if (ftruncate(fd, (off_t)e.size) != 0) {
      close(fd);
      shm_unlink(name.c_str());
      return false;
    }
    bool ok = true;
    if (e.size > 0) {
      void *dst = mmap(nullptr, e.size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
      close(fd);
      if (dst == MAP_FAILED) {
        shm_unlink(name.c_str());
        return false;
      }
      int in = open(e.spill_path.c_str(), O_RDONLY);
      ok = in >= 0 && ReadExact(in, dst, e.size);
      if (in >= 0) close(in);
      munmap(dst, e.size);
    } else {
      close(fd);
    }
    if (!ok) {
      shm_unlink(name.c_str());
      return false;
    }
    unlink(e.spill_path.c_str());
    e.shm_name = name;
    e.spilled = false;
    e.spill_path.clear();
    used_ += e.size;
    g_counters.Inc("objects_restored");
    return true;
  }

  static std::string Hex(const std::string &raw) {
    static const char *d = "0123456789abcdef";
    std::string out;
    for (unsigned char c : raw) {
      out.push_back(d[c >> 4]);
      out.push_back(d[c & 15]);
    }
    return out;
  }

  std::mutex mu_;
  std::condition_variable sealed_cv_;
  std::unordered_map<std::string, ObjectEntry> objects_;
  std::unordered_set<std::string> tombstones_;
  uint64_t capacity_;
  std::string spill_dir_;
  uint64_t min_spill_ = 0;  // batch floor per spill pass (config
                            // min_spilling_size)
  uint64_t used_ = 0;
  uint64_t tick_ = 0;
  uint64_t seq_ = 0;
  bool closing_ = false;
  // notification stream state
  std::mutex subs_mu_;
  std::vector<int> sub_fds_;
  std::deque<std::string> events_;
  std::condition_variable events_cv_;
  bool stopping_ = false;
  std::thread notifier_;
};

bool ReadExact(int fd, void *buf, size_t n) {
  char *p = (char *)buf;
  while (n > 0) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

bool WriteExact(int fd, const void *buf, size_t n) {
  const char *p = (const char *)buf;
  while (n > 0) {
    ssize_t r = write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

void SendResp(int fd, uint8_t status, const std::string &payload = "") {
  uint32_t len = 1 + (uint32_t)payload.size();
  std::string msg;
  msg.reserve(4 + len);
  msg.append((char *)&len, 4);
  msg.push_back((char)status);
  msg.append(payload);
  WriteExact(fd, msg.data(), msg.size());
}

std::atomic<bool> g_shutdown{false};
std::atomic<int> g_srv_fd{-1};

void ServeClient(Store *store, int fd) {
  // Objects this connection created but has not yet sealed; aborted on
  // disconnect so a crashed creator never leaves a permanently-unsealed
  // object that wedges getters (reference: plasma AbortObject on client
  // disconnect, plasma/store.cc DisconnectClient).
  std::unordered_set<std::string> unsealed;
  for (;;) {
    uint32_t len;
    if (!ReadExact(fd, &len, 4)) break;
    std::string req(len, '\0');
    if (!ReadExact(fd, &req[0], len)) break;
    if (len < 1 + ID_SIZE) {
      SendResp(fd, ST_ERR);
      continue;
    }
    uint8_t op = (uint8_t)req[0];
    std::string id = req.substr(1, ID_SIZE);
    const char *payload = req.data() + 1 + ID_SIZE;
    size_t payload_len = len - 1 - ID_SIZE;

    switch (op) {
      case OP_CREATE: {
        if (payload_len < 8) {
          SendResp(fd, ST_ERR);
          break;
        }
        uint64_t size;
        memcpy(&size, payload, 8);
        std::string name;
        uint8_t st = store->Create(id, size, &name);
        if (st == ST_OK) unsealed.insert(id);
        SendResp(fd, st, st == ST_OK ? name : "");
        break;
      }
      case OP_SEAL: {
        bool pin = payload_len >= 1 && payload[0] != 0;
        uint8_t st = store->Seal(id, pin);
        if (st == ST_OK) unsealed.erase(id);
        SendResp(fd, st);
        break;
      }
      case OP_GET: {
        uint64_t timeout_ms = 0;
        if (payload_len >= 8) memcpy(&timeout_ms, payload, 8);
        std::string name;
        uint64_t size = 0;
        uint8_t st = store->Get(id, timeout_ms, &name, &size);
        if (st == ST_OK) {
          std::string out((char *)&size, 8);
          out += name;
          SendResp(fd, st, out);
        } else {
          SendResp(fd, st);
        }
        break;
      }
      case OP_RELEASE:
        SendResp(fd, store->Release(id));
        break;
      case OP_DELETE:
        SendResp(fd, store->Delete(id));
        break;
      case OP_CONTAINS:
        SendResp(fd, store->Contains(id));
        break;
      case OP_LIST: {
        auto ids = store->List();
        std::string out;
        uint32_t n = (uint32_t)ids.size();
        out.append((char *)&n, 4);
        for (auto &s : ids) out += s;
        SendResp(fd, ST_OK, out);
        break;
      }
      case OP_STATS: {
        uint64_t used, cap, count;
        store->Stats(&used, &cap, &count);
        std::string out;
        out.append((char *)&used, 8);
        out.append((char *)&cap, 8);
        out.append((char *)&count, 8);
        SendResp(fd, ST_OK, out);
        break;
      }
      case OP_ABORT:
        store->Abort(id);
        unsealed.erase(id);
        SendResp(fd, ST_OK);
        break;
      case OP_WAIT: {
        if (payload_len < 16) {
          SendResp(fd, ST_ERR);
          break;
        }
        uint64_t timeout_ms;
        uint32_t k, n;
        memcpy(&timeout_ms, payload, 8);
        memcpy(&k, payload + 8, 4);
        memcpy(&n, payload + 12, 4);
        if (payload_len < 16 + (size_t)n * ID_SIZE) {
          SendResp(fd, ST_ERR);
          break;
        }
        std::vector<std::string> ids;
        ids.reserve(n);
        for (uint32_t i = 0; i < n; i++)
          ids.emplace_back(payload + 16 + i * ID_SIZE, ID_SIZE);
        auto present = store->WaitAny(ids, k, timeout_ms);
        std::string out;
        uint32_t m = (uint32_t)present.size();
        out.append((char *)&m, 4);
        for (auto &s : present) out += s;
        SendResp(fd, ST_OK, out);
        break;
      }
      case OP_PIN:
        SendResp(fd, store->Pin(id));
        break;
      case OP_UNPIN:
        SendResp(fd, store->Unpin(id));
        break;
      case OP_SUBSCRIBE:
        // Connection becomes a push-only event stream owned by the
        // notifier thread; stop reading requests and do NOT close the fd.
        // Subscribe() acks internally, ordered against notifier writes.
        store->Subscribe(fd);
        return;
      case OP_SHUTDOWN:
        SendResp(fd, ST_OK);
        g_shutdown = true;
        // Unblock the accept() loop so the daemon can exit.
        if (g_srv_fd >= 0) shutdown(g_srv_fd.load(), SHUT_RDWR);
        close(fd);
        return;
      default:
        SendResp(fd, ST_ERR);
    }
  }
  for (const auto &id : unsealed) store->Abort(id);
  close(fd);
}

}  // namespace

Store *g_store = nullptr;
const char *g_sock_path = nullptr;

void HandleTerm(int) {
  // Async-signal-safe only: flag shutdown and wake the accept loop; the
  // main thread does the real cleanup (UnlinkAll takes a mutex, which must
  // never happen inside a signal handler).
  g_shutdown = true;
  if (g_srv_fd >= 0) shutdown(g_srv_fd.load(), SHUT_RDWR);
}

int main(int argc, char **argv) {
  if (argc < 3) {
    fprintf(stderr,
            "usage: %s <socket_path> <capacity_bytes> [spill_dir] "
            "[min_spill_bytes]\n",
            argv[0]);
    return 1;
  }
  const char *sock_path = argv[1];
  uint64_t capacity = strtoull(argv[2], nullptr, 10);
  std::string spill_dir = argc > 3 ? argv[3] : "";
  uint64_t min_spill = argc > 4 ? strtoull(argv[4], nullptr, 10) : 0;
  if (!spill_dir.empty() && mkdir(spill_dir.c_str(), 0700) != 0 &&
      errno != EEXIST) {
    rt_util::Event("WARNING", "store_spill_dir_unusable",
                   "\"dir\":\"" + rt_util::JsonEscape(spill_dir) + "\"");
    spill_dir.clear();
  }
  if (!spill_dir.empty()) {
    // per-daemon subdir: several stores may share one configured spill
    // root (e.g. every node of a local cluster) and the same object id can
    // exist in more than one store — files must never clobber across stores
    spill_dir += "/pid" + std::to_string(getpid());
    if (mkdir(spill_dir.c_str(), 0700) != 0 && errno != EEXIST) {
      rt_util::Event("WARNING", "store_spill_dir_unusable",
                     "\"dir\":\"" + rt_util::JsonEscape(spill_dir) + "\"");
      spill_dir.clear();
    }
  }
  rt_util::Event("INFO", "store_started",
                 "\"capacity_bytes\":" + std::to_string(capacity) +
                 ",\"spill\":" + (spill_dir.empty() ? "false" : "true"));
  Store store(capacity, spill_dir, min_spill);
  g_store = &store;
  g_sock_path = sock_path;
  signal(SIGTERM, HandleTerm);
  signal(SIGINT, HandleTerm);

  unlink(sock_path);
  int srv = socket(AF_UNIX, SOCK_STREAM, 0);
  if (srv < 0) {
    perror("socket");
    return 1;
  }
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  strncpy(addr.sun_path, sock_path, sizeof(addr.sun_path) - 1);
  if (bind(srv, (sockaddr *)&addr, sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(srv, 128) != 0) {
    perror("listen");
    return 1;
  }
  g_srv_fd = srv;
  store.StartNotifier();
  // Readiness handshake: parent waits for this line.
  printf("READY\n");
  fflush(stdout);

  while (!g_shutdown) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) break;
    // detach immediately: connections may be ephemeral (one per wait()
    // window) — an unbounded join-list would leak a handle per connection
    std::thread(ServeClient, &store, fd).detach();
  }
  store.StopNotifier();
  store.UnlinkAll();
  unlink(sock_path);
  rt_util::Event("INFO", "store_shutdown", g_counters.ToJsonFields());
  return 0;
}
