"""Actor API: ActorClass (decorated class) and ActorHandle.

Equivalent of the reference's actor layer
(reference: python/ray/actor.py — ActorClass:384, ActorHandle:1025,
ActorClass._remote:667 builds the creation TaskSpec; actor method calls
become ordered actor tasks). Handles are picklable: a deserialized handle
routes through the GCS actor table to the hosting raylet.
"""
from __future__ import annotations

from typing import Any

from ray_tpu._private import task_spec as ts
from ray_tpu._private.config import global_config as _global_config
from ray_tpu._private.ids import ActorID
from ray_tpu._private.worker import global_worker
from ray_tpu.exceptions import ActorDiedError


class ActorClass:
    def __init__(self, cls, *, num_cpus=1, num_tpus=0, resources=None,
                 max_restarts=None, name=None, lifetime=None,
                 scheduling_strategy=None, runtime_env=None, max_concurrency=1):
        self._cls = cls
        self._class_name = cls.__name__
        self._class_blob = ts.dumps_function(cls)
        self._resources = dict(resources or {})
        self._resources.setdefault("CPU", float(num_cpus))
        if num_tpus:
            self._resources["TPU"] = float(num_tpus)
        self._max_restarts = max_restarts  # None -> cluster default at .remote()
        self._name = name
        self._lifetime = lifetime
        self._scheduling_strategy = scheduling_strategy
        from ray_tpu._private.runtime_env import validate_runtime_env

        validate_runtime_env(runtime_env)
        self._runtime_env = runtime_env
        self._max_concurrency = max(1, int(max_concurrency))

    def __call__(self, *a, **k):
        raise TypeError(
            f"Actor class {self._class_name} cannot be instantiated directly; "
            f"use {self._class_name}.remote(...)"
        )

    def options(self, **opts) -> "ActorClass":
        clone = ActorClass.__new__(ActorClass)
        clone.__dict__.update(self.__dict__)
        res = dict(clone._resources)
        if "num_cpus" in opts:
            res["CPU"] = float(opts["num_cpus"])
        if "num_tpus" in opts:
            res["TPU"] = float(opts["num_tpus"])
        if "resources" in opts:
            res.update(opts["resources"])
        clone._resources = res
        for key in ("max_restarts", "name", "lifetime", "scheduling_strategy",
                    "runtime_env", "max_concurrency"):
            if key in opts:
                setattr(clone, "_" + key, opts[key])
        clone._max_concurrency = max(1, int(clone._max_concurrency))
        if "runtime_env" in opts:
            from ray_tpu._private.runtime_env import validate_runtime_env

            validate_runtime_env(clone._runtime_env)
        return clone

    def remote(self, *args, **kwargs) -> "ActorHandle":
        worker = global_worker()
        actor_id = ActorID.of(worker.job_id)
        # cluster-wide default (config.max_actor_restarts_default) when the
        # decorator didn't pin one; resolved at CREATION so a later
        # init(_system_config=...) override reaches already-decorated classes
        max_restarts = (self._max_restarts if self._max_restarts is not None
                        else _global_config().max_actor_restarts_default)
        worker.gcs.call(
            "register_actor",
            {
                "actor_id": actor_id.binary(),
                "class_name": self._class_name,
                "name": self._name,
                "max_restarts": max_restarts,
            },
        )
        from ray_tpu.remote_function import _strategy_fields

        placement, scheduling = _strategy_fields(self._scheduling_strategy)
        spec = ts.make_task_spec(
            task_id=worker.new_task_id(),
            job_id=worker.job_id,
            name=f"{self._class_name}.__init__",
            task_type=ts.ACTOR_CREATION,
            function_blob=self._class_blob,
            args=args,
            kwargs=kwargs,
            num_returns=1,
            resources=self._resources,
            actor_id=actor_id,
            max_restarts=max_restarts,
            placement=placement,
            scheduling=scheduling,
            runtime_env=self._runtime_env,
            max_concurrency=self._max_concurrency,
        )
        worker.submit_task(spec)
        return ActorHandle(actor_id, self._class_name)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns=1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def options(self, *, num_returns=1) -> "ActorMethod":
        return ActorMethod(self._handle, self._method_name, num_returns)

    def remote(self, *args, **kwargs):
        worker = global_worker()
        h = self._handle
        raylet_addr = worker.actor_raylet_address(h._actor_id)
        # generator methods stream exactly like normal tasks (reference:
        # _raylet.pyx streaming generators work for actor tasks too)
        streaming = self._num_returns == "streaming"
        spec = ts.make_task_spec(
            task_id=ts.TaskID.for_actor_task(h._actor_id),
            job_id=worker.job_id,
            name=f"{h._class_name}.{self._method_name}",
            task_type=ts.ACTOR_TASK,
            method_name=self._method_name,
            args=args,
            kwargs=kwargs,
            num_returns=1 if streaming else self._num_returns,
            streaming=streaming,
            resources={},
            actor_id=h._actor_id,
            seqno=worker.next_actor_seqno(h._actor_id),
        )
        from ray_tpu.util.tracing import current_context

        trace_ctx = current_context()
        if trace_ctx is not None:
            spec["trace_ctx"] = trace_ctx
        try:
            refs = worker.submit_actor_task(spec, raylet_addr)
        except ConnectionError:
            worker.invalidate_actor_cache(h._actor_id)
            raise ActorDiedError(h._actor_id.hex(), "raylet connection lost")
        if streaming:
            from ray_tpu._private.generator import ObjectRefGenerator

            return ObjectRefGenerator(refs[0], spec)
        return refs[0] if self._num_returns == 1 else refs


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str = ""):
        self._actor_id = actor_id
        self._class_name = class_name

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name))

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id


def get_actor(name: str) -> ActorHandle:
    """Look up a named actor (reference: ray.get_actor)."""
    worker = global_worker()
    r = worker.gcs.call("get_named_actor", {"name": name})
    if r["actor_id"] is None:
        raise ValueError(f"no actor named {name!r}")
    return ActorHandle(ActorID(r["actor_id"]), r["actor"].get("class_name", ""))


def kill(handle: ActorHandle) -> None:
    """Forcefully terminate an actor (reference: ray.kill)."""
    worker = global_worker()
    try:
        addr = worker.actor_raylet_address(handle._actor_id, timeout=5)
    except (TimeoutError, ActorDiedError):
        return
    client = worker._peer(addr) if addr != worker.raylet.address else worker.raylet
    client.call("kill_actor", {"actor_id": handle._actor_id.binary()})
    worker.invalidate_actor_cache(handle._actor_id)
