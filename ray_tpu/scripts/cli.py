"""CLI entry points: status / list / summary / timeline / jobs / bench.

Equivalent of the reference's CLI surface
(reference: python/ray/scripts/scripts.py `ray start|status|...`:548,1259;
state CLI python/ray/experimental/state/state_cli.py `ray list|summary`;
job CLI dashboard/modules/job/cli.py; `ray microbenchmark`
python/ray/_private/ray_perf.py). Usage:

    python -m ray_tpu.scripts.cli status --address <gcs>
    python -m ray_tpu.scripts.cli list tasks|actors|nodes --address <gcs>
    python -m ray_tpu.scripts.cli summary --address <gcs>
    python -m ray_tpu.scripts.cli timeline out.json --address <gcs>
    python -m ray_tpu.scripts.cli microbenchmark
    python -m ray_tpu.scripts.cli jobs submit|status|logs|list ...
"""
from __future__ import annotations

import argparse
import json
import sys


def _connect(address: str | None):
    import ray_tpu

    if address:
        ray_tpu.init(address=address)
    elif not ray_tpu.is_initialized():
        raise SystemExit("--address required (no local cluster in this process)")


def cmd_status(args) -> None:
    _connect(args.address)
    from ray_tpu.util import state

    print(json.dumps(state.summary(), indent=2, default=str))


def cmd_list(args) -> None:
    _connect(args.address)
    from ray_tpu.util import state

    kind = args.kind
    rows = {
        "tasks": state.list_tasks,
        "actors": state.list_actors,
        "nodes": state.list_nodes,
    }[kind]()
    print(json.dumps(rows, indent=2, default=str))


def cmd_summary(args) -> None:
    _connect(args.address)
    from ray_tpu.util import state

    print(json.dumps(state.summarize_tasks(), indent=2, default=str))


def cmd_timeline(args) -> None:
    _connect(args.address)
    from ray_tpu.util import state

    state.timeline(args.output)
    print(f"wrote chrome trace to {args.output} (open in chrome://tracing)")


def cmd_microbenchmark(args) -> None:
    from ray_tpu._private.ray_perf import main as perf_main

    perf_main()


def cmd_jobs(args) -> None:
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(args.dashboard)
    if args.jobs_cmd == "submit":
        print(client.submit_job(entrypoint=args.entrypoint))
    elif args.jobs_cmd == "status":
        print(json.dumps(client.get_job_info(args.job_id), indent=2))
    elif args.jobs_cmd == "logs":
        print(client.get_job_logs(args.job_id))
    elif args.jobs_cmd == "stop":
        print(client.stop_job(args.job_id))
    elif args.jobs_cmd == "list":
        print(json.dumps(client.list_jobs(), indent=2))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ray_tpu", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    def with_address(sp):
        sp.add_argument("--address", help="GCS address host:port")
        return sp

    with_address(sub.add_parser("status")).set_defaults(fn=cmd_status)
    lp = with_address(sub.add_parser("list"))
    lp.add_argument("kind", choices=["tasks", "actors", "nodes"])
    lp.set_defaults(fn=cmd_list)
    with_address(sub.add_parser("summary")).set_defaults(fn=cmd_summary)
    tp = with_address(sub.add_parser("timeline"))
    tp.add_argument("output")
    tp.set_defaults(fn=cmd_timeline)
    sub.add_parser("microbenchmark").set_defaults(fn=cmd_microbenchmark)
    jp = sub.add_parser("jobs")
    jp.add_argument("--dashboard", default="http://127.0.0.1:8265")
    jsub = jp.add_subparsers(dest="jobs_cmd", required=True)
    sp = jsub.add_parser("submit")
    sp.add_argument("entrypoint")
    for name in ("status", "logs", "stop"):
        x = jsub.add_parser(name)
        x.add_argument("job_id")
    jsub.add_parser("list")
    jp.set_defaults(fn=cmd_jobs)
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
