"""CLI entry points: status / list / summary / timeline / jobs / bench.

Equivalent of the reference's CLI surface
(reference: python/ray/scripts/scripts.py `ray start|status|...`:548,1259;
state CLI python/ray/experimental/state/state_cli.py `ray list|summary`;
job CLI dashboard/modules/job/cli.py; `ray microbenchmark`
python/ray/_private/ray_perf.py). Usage:

    python -m ray_tpu.scripts.cli start --head [--port P] [--block]
    python -m ray_tpu.scripts.cli start --address <gcs> [--block]
    python -m ray_tpu.scripts.cli stop
    python -m ray_tpu.scripts.cli status --address <gcs>
    python -m ray_tpu.scripts.cli list tasks|actors|nodes --address <gcs>
    python -m ray_tpu.scripts.cli summary --address <gcs>
    python -m ray_tpu.scripts.cli timeline out.json --address <gcs>
    python -m ray_tpu.scripts.cli microbenchmark
    python -m ray_tpu.scripts.cli jobs submit|status|logs|list ...
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys


def _connect(address: str | None):
    import ray_tpu

    if address:
        ray_tpu.init(address=address)
    elif not ray_tpu.is_initialized():
        raise SystemExit("--address required (no local cluster in this process)")


def cmd_start(args) -> None:
    """Start a cluster node as a real OS process (reference: `ray start`,
    scripts.py:548). --block runs it in the foreground; the default spawns
    a detached node process and returns once it reports ready."""
    if bool(args.head) == bool(args.address):
        raise SystemExit("exactly one of --head / --address is required")
    node_argv = [sys.executable, "-m", "ray_tpu._private.node_main"]
    if args.head:
        node_argv += ["--head", "--port", str(args.port)]
    else:
        node_argv += ["--address", args.address]
    if args.num_cpus is not None:
        node_argv += ["--num-cpus", str(args.num_cpus)]
    if args.num_tpus is not None:
        node_argv += ["--num-tpus", str(args.num_tpus)]
    if args.object_store_memory is not None:
        node_argv += ["--object-store-memory", str(args.object_store_memory)]
    if args.client_server_port is not None:
        node_argv += ["--client-server-port", str(args.client_server_port)]
    if args.resources:
        node_argv += ["--resources", args.resources]
    if args.info_file:
        # default: node_main writes a per-pid file under the nodes dir
        node_argv += ["--info-file", args.info_file]

    if args.block:
        os.execv(sys.executable, node_argv)

    proc = subprocess.Popen(
        node_argv, stdout=subprocess.PIPE, stderr=None, start_new_session=True
    )
    line = proc.stdout.readline().decode()
    if "RAY_TPU_NODE_READY" not in line:
        raise SystemExit(f"node failed to start: {line!r}")
    info = json.loads(line.split(" ", 1)[1])
    kind = "head" if args.head else "worker"
    print(f"started {kind} node pid={info['pid']} gcs={info['gcs_address']}")
    if args.head:
        print(f"to join:    ray_tpu start --address {info['gcs_address']}")
        print(f"to connect: ray_tpu.init(address=\"{info['gcs_address']}\")")
        if info.get("client_address"):
            print("remote drivers: ray_tpu.init(address="
                  f"\"ray://{info['client_address']}\")")


def cmd_stop(args) -> None:
    """Stop node processes on this host. With --info-file, just that node;
    otherwise every node recorded in the default nodes dir (the reference's
    `ray stop` stops all local ray processes)."""
    import glob

    from ray_tpu._private.node_main import default_info_dir

    if args.info_file:
        info_files = [args.info_file]
    else:
        info_files = sorted(glob.glob(os.path.join(default_info_dir(), "*.json")))
        if not info_files:
            raise SystemExit(f"no nodes recorded in {default_info_dir()}")
    for info_file in info_files:
        try:
            with open(info_file) as f:
                info = json.load(f)
        except OSError:
            continue
        try:
            os.kill(info["pid"], signal.SIGTERM)
            print(f"sent SIGTERM to node pid={info['pid']}")
        except ProcessLookupError:
            print(f"node pid={info['pid']} already gone")
        try:
            os.remove(info_file)
        except OSError:
            pass


def cmd_status(args) -> None:
    _connect(args.address)
    from ray_tpu.util import state

    print(json.dumps(state.summary(), indent=2, default=str))


def cmd_list(args) -> None:
    _connect(args.address)
    from ray_tpu.util import state

    kind = args.kind
    rows = {
        "tasks": state.list_tasks,
        "actors": state.list_actors,
        "nodes": state.list_nodes,
    }[kind]()
    print(json.dumps(rows, indent=2, default=str))


def cmd_summary(args) -> None:
    _connect(args.address)
    from ray_tpu.util import state

    print(json.dumps(state.summarize_tasks(), indent=2, default=str))


def cmd_timeline(args) -> None:
    _connect(args.address)
    from ray_tpu.util import state

    state.timeline(args.output)
    print(f"wrote chrome trace to {args.output} (open in chrome://tracing)")


def cmd_microbenchmark(args) -> None:
    from ray_tpu._private.ray_perf import main as perf_main

    perf_main()


def cmd_jobs(args) -> None:
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(args.dashboard)
    if args.jobs_cmd == "submit":
        print(client.submit_job(entrypoint=args.entrypoint))
    elif args.jobs_cmd == "status":
        print(json.dumps(client.get_job_info(args.job_id), indent=2))
    elif args.jobs_cmd == "logs":
        print(client.get_job_logs(args.job_id))
    elif args.jobs_cmd == "stop":
        print(client.stop_job(args.job_id))
    elif args.jobs_cmd == "list":
        print(json.dumps(client.list_jobs(), indent=2))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ray_tpu", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    def with_address(sp):
        sp.add_argument("--address", help="GCS address host:port")
        return sp

    st = sub.add_parser("start")
    st.add_argument("--head", action="store_true")
    st.add_argument("--address", help="existing GCS address (join as worker)")
    st.add_argument("--port", type=int, default=0, help="GCS port (head)")
    st.add_argument("--num-cpus", type=float, default=None)
    st.add_argument("--num-tpus", type=float, default=None)
    st.add_argument("--object-store-memory", type=int, default=None)
    st.add_argument("--client-server-port", type=int, default=None,
                    help="ray:// port (head; default 10001, -1 disables)")
    st.add_argument("--resources", default=None, help="JSON dict")
    st.add_argument("--info-file", default=None)
    st.add_argument("--block", action="store_true", help="run in foreground")
    st.set_defaults(fn=cmd_start)
    sp_stop = sub.add_parser("stop")
    sp_stop.add_argument("--info-file", default=None)
    sp_stop.set_defaults(fn=cmd_stop)

    with_address(sub.add_parser("status")).set_defaults(fn=cmd_status)
    lp = with_address(sub.add_parser("list"))
    lp.add_argument("kind", choices=["tasks", "actors", "nodes"])
    lp.set_defaults(fn=cmd_list)
    with_address(sub.add_parser("summary")).set_defaults(fn=cmd_summary)
    tp = with_address(sub.add_parser("timeline"))
    tp.add_argument("output")
    tp.set_defaults(fn=cmd_timeline)
    sub.add_parser("microbenchmark").set_defaults(fn=cmd_microbenchmark)
    jp = sub.add_parser("jobs")
    jp.add_argument("--dashboard", default="http://127.0.0.1:8265")
    jsub = jp.add_subparsers(dest="jobs_cmd", required=True)
    sp = jsub.add_parser("submit")
    sp.add_argument("entrypoint")
    for name in ("status", "logs", "stop"):
        x = jsub.add_parser(name)
        x.add_argument("job_id")
    jsub.add_parser("list")
    jp.set_defaults(fn=cmd_jobs)
    return p


def main(argv=None) -> None:
    args = build_parser().parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
