"""@ray_tpu.remote functions.

Equivalent of the reference's RemoteFunction
(reference: python/ray/remote_function.py:40, _remote at :257 — wraps the
user function, pickles it once, builds TaskSpecs per call, supports
.options(...) overrides).
"""
from __future__ import annotations

from typing import Any

from ray_tpu._private import task_spec as ts
from ray_tpu._private.worker import global_worker


class RemoteFunction:
    def __init__(self, func, *, num_cpus=1, num_tpus=0, num_returns=1,
                 max_retries=0, resources=None, scheduling_strategy=None,
                 runtime_env=None, name=None):
        self._function = func
        self._name = name or getattr(func, "__name__", "anonymous")
        self._function_blob = ts.dumps_function(func)
        self._num_returns = num_returns
        self._max_retries = max_retries
        self._resources = dict(resources or {})
        if num_cpus is not None:
            self._resources.setdefault("CPU", float(num_cpus))
        if num_tpus:
            self._resources["TPU"] = float(num_tpus)
        self._scheduling_strategy = scheduling_strategy
        from ray_tpu._private.runtime_env import validate_runtime_env

        validate_runtime_env(runtime_env)
        self._runtime_env = runtime_env

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._name}' cannot be called directly; "
            f"use {self._name}.remote(...)"
        )

    def options(self, **opts) -> "RemoteFunction":
        clone = RemoteFunction.__new__(RemoteFunction)
        clone.__dict__.update(self.__dict__)
        if "num_returns" in opts:
            clone._num_returns = opts["num_returns"]
        if "max_retries" in opts:
            clone._max_retries = opts["max_retries"]
        if "name" in opts:
            clone._name = opts["name"]
        if "scheduling_strategy" in opts:
            clone._scheduling_strategy = opts["scheduling_strategy"]
        if "runtime_env" in opts:
            from ray_tpu._private.runtime_env import validate_runtime_env

            validate_runtime_env(opts["runtime_env"])
            clone._runtime_env = opts["runtime_env"]
        res = dict(clone._resources)
        if "num_cpus" in opts:
            res["CPU"] = float(opts["num_cpus"])
        if "num_tpus" in opts:
            res["TPU"] = float(opts["num_tpus"])
        if "resources" in opts:
            res.update(opts["resources"])
        clone._resources = res
        return clone

    def remote(self, *args, **kwargs):
        worker = global_worker()
        placement, scheduling = _strategy_fields(self._scheduling_strategy)
        streaming = self._num_returns == "streaming"
        spec = ts.make_task_spec(
            task_id=worker.new_task_id(),
            job_id=worker.job_id,
            name=self._name,
            task_type=ts.NORMAL,
            function_blob=self._function_blob,
            args=args,
            kwargs=kwargs,
            num_returns=1 if streaming else self._num_returns,
            streaming=streaming,
            resources=self._resources,
            max_retries=self._max_retries,
            placement=placement,
            scheduling=scheduling,
            runtime_env=self._runtime_env,
        )
        from ray_tpu.util.tracing import current_context

        trace_ctx = current_context()
        if trace_ctx is not None:
            spec["trace_ctx"] = trace_ctx
        refs = worker.submit_task(spec)
        if streaming:
            from ray_tpu._private.generator import ObjectRefGenerator

            return ObjectRefGenerator(refs[0], spec)
        return refs[0] if self._num_returns == 1 else refs


def _strategy_fields(strategy: Any) -> tuple[dict | None, dict]:
    """Translate a scheduling-strategy object into spec fields."""
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
        PlacementGroupSchedulingStrategy,
    )

    if strategy is None:
        return None, {"type": ts.SCHED_DEFAULT}
    if strategy == "SPREAD":
        return None, {"type": ts.SCHED_SPREAD}
    if strategy == "DEFAULT":
        return None, {"type": ts.SCHED_DEFAULT}
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        return (
            {
                "pg": strategy.placement_group.id.binary(),
                "bundle": strategy.placement_group_bundle_index,
            },
            {"type": ts.SCHED_DEFAULT},
        )
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return None, {
            "type": ts.SCHED_NODE_AFFINITY,
            "node_id": strategy.node_id,
            "soft": strategy.soft,
        }
    raise ValueError(f"unknown scheduling strategy: {strategy!r}")


def remote_decorator(*args, **kwargs):
    """Implements @ray_tpu.remote / @ray_tpu.remote(**options) for both
    functions and classes (reference: python/ray/_private/worker.py:3027)."""
    from ray_tpu.actor import ActorClass
    import inspect

    def wrap(target):
        if inspect.isclass(target):
            return ActorClass(target, **kwargs)
        return RemoteFunction(target, **kwargs)

    if len(args) == 1 and not kwargs and (callable(args[0]) or inspect.isclass(args[0])):
        return wrap(args[0])
    if args:
        raise TypeError("@remote accepts only keyword options")
    return wrap
