"""TuneController: the experiment event loop over trial actors.

Equivalent of the reference's TuneController (reference: python/ray/tune/
execution/tune_controller.py:81 — event loop over RayActorManager creating
one actor per trial, draining results, applying scheduler decisions,
persisting experiment state for resume). Trials here are actors running the
user trainable on a background thread; the controller polls their report
buffers, mirroring the Train WorkerGroup pattern.
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional

import ray_tpu
from ray_tpu._private import task_spec as ts
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.schedulers import (
    CONTINUE, PAUSE, STOP, FIFOScheduler, TrialScheduler,
)
from ray_tpu.tune.search import BasicVariantGenerator, Searcher
from ray_tpu.tune.trial import (
    ERROR, PAUSED, PENDING, RUNNING, TERMINATED, Trial,
)


@ray_tpu.remote
class _TrialActor:
    """Runs one trial's trainable on a background thread; poll() drains."""

    def __init__(self, fn_blob: bytes, config: dict, trial_id: str,
                 trial_dir: str, restore_path: str | None, start_iteration: int):
        import threading

        from ray_tpu.tune import session as tune_session

        self._session = tune_session._TuneSession(
            trial_id, trial_dir, restore_path, start_iteration
        )
        tune_session.init_session(self._session)
        fn = ts.loads_function(fn_blob)

        def runner():
            try:
                fn(config)
                self._session.finish()
            except BaseException as e:  # noqa: BLE001
                import traceback

                self._session.finish(
                    f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
                )

        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()

    def poll(self, since: int) -> dict:
        reports, done, error = self._session.drain(since)
        return {"reports": reports, "done": done, "error": error}


class TuneController:
    def __init__(
        self,
        trainable: Callable,
        *,
        searcher: Searcher,
        scheduler: TrialScheduler | None,
        metric: str,
        mode: str,
        experiment_dir: str,
        max_concurrent_trials: int | None = None,
        resources_per_trial: dict | None = None,
        max_failures: int = 0,
        poll_interval: float = 0.05,
        reports_per_step: int = 8,
    ):
        self.fn_blob = ts.dumps_function(trainable)
        self.searcher = searcher
        self.scheduler = scheduler or FIFOScheduler()
        self.scheduler.set_search_properties(metric, mode)
        self.metric, self.mode = metric, mode
        self.experiment_dir = experiment_dir
        os.makedirs(experiment_dir, exist_ok=True)
        self.resources_per_trial = resources_per_trial or {"CPU": 1}
        if max_concurrent_trials is None:
            # fit = min over every requested resource of total/requested, so
            # TPU-bound trials don't oversubscribe chips just because CPUs
            # are plentiful
            total = ray_tpu.cluster_resources()
            fits = [
                int(total.get(r, 0) // amt)
                for r, amt in self.resources_per_trial.items()
                if amt > 0
            ]
            max_concurrent_trials = max(1, min(fits)) if fits else 1
        self.max_concurrent = max_concurrent_trials
        self.max_failures = max_failures
        self.poll_interval = poll_interval
        # fairness cap: drain at most this many reports per trial per step so
        # a fast trial cannot flood the scheduler before its peers report
        # (rung/quantile comparisons need interleaved streams)
        self.reports_per_step = reports_per_step
        self.trials: list[Trial] = []
        self._actors: dict[str, object] = {}
        self._cursors: dict[str, int] = {}
        self._failures: dict[str, int] = {}
        self._searcher_done = False

    # ---- experiment state persistence (reference: tune/execution/
    # experiment_state.py — enables Tuner.restore) ----

    def _state_path(self) -> str:
        return os.path.join(self.experiment_dir, "experiment_state.json")

    def save_state(self) -> None:
        tmp = self._state_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"trials": [t.to_json() for t in self.trials]}, f)
        os.replace(tmp, self._state_path())

    def load_state(self) -> bool:
        if not os.path.exists(self._state_path()):
            return False
        with open(self._state_path()) as f:
            state = json.load(f)
        for d in state["trials"]:
            t = Trial.from_json(d, self.experiment_dir)
            if t.status in (RUNNING, PENDING, PAUSED):
                # resume from last checkpoint if any
                t.status = PENDING
                t.restore_path = t.checkpoint_path
            self.trials.append(t)
            if t.status in (RUNNING, PENDING, PAUSED):
                self.scheduler.on_trial_add(t)
        return True

    # ---- event loop ----

    def _launch(self, trial: Trial) -> None:
        actor = _TrialActor.options(
            num_cpus=self.resources_per_trial.get("CPU", 1),
            num_tpus=self.resources_per_trial.get("TPU", 0),
        ).remote(
            self.fn_blob, trial.config, trial.trial_id, trial.trial_dir,
            trial.restore_path, trial.iteration,
        )
        trial.restore_path = None
        trial.status = RUNNING
        self._actors[trial.trial_id] = actor
        self._cursors[trial.trial_id] = 0

    def _stop_actor(self, trial: Trial) -> None:
        actor = self._actors.pop(trial.trial_id, None)
        self._cursors.pop(trial.trial_id, None)
        if actor is not None:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass

    def _maybe_add_trials(self) -> None:
        import uuid

        while not self._searcher_done:
            n_active = sum(1 for t in self.trials if t.status in (PENDING, RUNNING))
            if n_active >= self.max_concurrent * 2:
                break
            # mint the trial id first so the searcher sees the same id in
            # suggest() and on_trial_complete()
            tid = uuid.uuid4().hex[:8]
            cfg = self.searcher.suggest(tid)
            if cfg is None:
                self._searcher_done = True
                break
            trial = Trial(config=cfg, experiment_dir=self.experiment_dir,
                          trial_id=tid)
            self.trials.append(trial)
            self.scheduler.on_trial_add(trial)

    def step(self) -> bool:
        """One controller iteration; returns False when the experiment is done."""
        self._maybe_add_trials()
        self._apply_pending_actions()
        running = [t for t in self.trials if t.status == RUNNING]
        # launch pending trials up to the concurrency cap
        for t in self.trials:
            if t.status == PENDING and len(running) < self.max_concurrent:
                self._launch(t)
                running.append(t)
        progressed = False
        for trial in list(running):
            actor = self._actors.get(trial.trial_id)
            if actor is None:
                continue
            try:
                out = ray_tpu.get(
                    actor.poll.remote(self._cursors[trial.trial_id]), timeout=30
                )
            except ray_tpu.exceptions.GetTimeoutError:
                # actor may still be queued behind busy resources (cold worker
                # spawn, contended chips) — not dead, just no progress yet
                continue
            except Exception as e:  # actor died
                self._on_trial_error(trial, f"trial actor died: {e}")
                continue
            reports = out["reports"]
            drained_all = len(reports) <= self.reports_per_step
            reports = reports[: self.reports_per_step]
            self._cursors[trial.trial_id] += len(reports)
            for rep in reports:
                progressed = True
                metrics = dict(rep["metrics"])
                trial.iteration = rep["iteration"]
                metrics.setdefault("training_iteration", trial.iteration)
                trial.last_result = metrics
                trial.results.append(metrics)
                if "checkpoint_path" in rep:
                    trial.checkpoint_path = rep["checkpoint_path"]
                decision = self.scheduler.on_trial_result(trial, metrics)
                if decision == STOP:
                    self._stop_actor(trial)
                    trial.status = TERMINATED
                    self.searcher.on_trial_complete(trial.trial_id, metrics)
                    break
                if decision == PAUSE:
                    # park at the checkpoint; the scheduler resumes/stops it
                    # later through pending_actions (synchronous bands)
                    self._stop_actor(trial)
                    trial.restore_path = trial.checkpoint_path
                    trial.status = PAUSED
                    break
                if decision == sched_mod.PopulationBasedTraining.EXPLOIT:
                    # scheduler already rewrote trial.config/restore_path
                    self._stop_actor(trial)
                    trial.status = PENDING
                    break
            if trial.status != RUNNING:
                continue
            if out["done"] and drained_all:
                progressed = True
                self._stop_actor(trial)
                if out["error"]:
                    self._on_trial_error(trial, out["error"])
                else:
                    trial.status = TERMINATED
                    self.scheduler.on_trial_complete(trial)
                    self.searcher.on_trial_complete(
                        trial.trial_id, trial.last_result
                    )
        if progressed:
            self.save_state()
        return any(
            t.status in (PENDING, RUNNING, PAUSED) for t in self.trials
        ) or (not self._searcher_done)

    def _apply_pending_actions(self) -> None:
        """Release trials the scheduler parked with PAUSE (sync HyperBand
        resume/stop verdicts land here, once per step)."""
        actions = self.scheduler.pending_actions()
        if not actions:
            return
        by_id = {t.trial_id: t for t in self.trials}
        for tid, verdict in actions.items():
            trial = by_id.get(tid)
            if trial is None or trial.status not in (PAUSED, RUNNING, PENDING):
                continue
            if verdict == "RESUME":
                if trial.status == PAUSED:
                    trial.status = PENDING
            elif verdict == "STOP":
                self._stop_actor(trial)
                trial.status = TERMINATED
                self.searcher.on_trial_complete(trial.trial_id,
                                                trial.last_result)

    def _on_trial_error(self, trial: Trial, error: str) -> None:
        self._stop_actor(trial)
        n = self._failures.get(trial.trial_id, 0)
        if n < self.max_failures:
            self._failures[trial.trial_id] = n + 1
            trial.restore_path = trial.checkpoint_path
            trial.status = PENDING
        else:
            trial.status = ERROR
            trial.error = error
            self.searcher.on_trial_complete(trial.trial_id, error=True)
            # a failed trial leaves any synchronous band it was part of —
            # otherwise paused peers wait on it forever
            self.scheduler.on_trial_complete(trial)

    def run(self) -> list[Trial]:
        while self.step():
            time.sleep(self.poll_interval)
        self.save_state()
        return self.trials
