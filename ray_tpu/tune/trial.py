"""Trial state.

Equivalent of the reference's Trial (reference: python/ray/tune/experiment/
trial.py:307 — id, config, status lifecycle PENDING→RUNNING→TERMINATED/
ERROR/PAUSED, last_result, checkpoint bookkeeping).
"""
from __future__ import annotations

import os
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclass
class Trial:
    config: dict
    experiment_dir: str
    trial_id: str = field(default_factory=lambda: uuid.uuid4().hex[:8])
    status: str = PENDING
    last_result: dict | None = None
    results: list = field(default_factory=list)
    error: str | None = None
    checkpoint_path: str | None = None
    # training_iteration observed so far (monotonic across pauses/restores)
    iteration: int = 0
    # set by PBT when the trial should restore from another trial's checkpoint
    restore_path: str | None = None

    @property
    def trial_dir(self) -> str:
        d = os.path.join(self.experiment_dir, f"trial_{self.trial_id}")
        os.makedirs(d, exist_ok=True)
        return d

    def metric_value(self, metric: str) -> Optional[float]:
        if self.last_result and metric in self.last_result:
            return float(self.last_result[metric])
        return None

    def to_json(self) -> dict:
        return {
            "trial_id": self.trial_id,
            "config": self.config,
            "status": self.status,
            "last_result": self.last_result,
            "error": self.error,
            "checkpoint_path": self.checkpoint_path,
            "iteration": self.iteration,
        }

    @classmethod
    def from_json(cls, d: dict, experiment_dir: str) -> "Trial":
        t = cls(config=d["config"], experiment_dir=experiment_dir,
                trial_id=d["trial_id"])
        t.status = d["status"]
        t.last_result = d.get("last_result")
        t.error = d.get("error")
        t.checkpoint_path = d.get("checkpoint_path")
        t.iteration = d.get("iteration", 0)
        return t
