"""ray_tpu.tune — hyperparameter search over trial actors.

Equivalent of the reference's Tune (reference: python/ray/tune — Tuner
tuner.py:59, TuneController execution/tune_controller.py:81, schedulers/,
search/). Trials are actors on the distributed core; TPU trials reserve
chips via trial resources so concurrent trials never share a chip.
"""
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandForBOHB,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    PopulationBasedTrainingReplay,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    SuggestAdapter,
    BasicVariantGenerator,
    Searcher,
    BayesOptSearcher,
    TPESearcher,
    TuneBOHB,
    choice,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.session import get_checkpoint, report
from ray_tpu.tune.trial import Trial
from ray_tpu.tune.tuner import (
    ResultGrid,
    TuneConfig,
    TuneResult,
    TuneRunConfig,
    Tuner,
)

__all__ = [
    "SuggestAdapter",
    "ASHAScheduler",
    "HyperBandForBOHB",
    "HyperBandScheduler",
    "PB2",
    "PopulationBasedTrainingReplay",
    "AsyncHyperBandScheduler",
    "BasicVariantGenerator",
    "FIFOScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "ResultGrid",
    "Searcher",
    "Trial",
    "TrialScheduler",
    "TuneConfig",
    "TuneResult",
    "TuneRunConfig",
    "Tuner",
    "choice",
    "get_checkpoint",
    "grid_search",
    "BayesOptSearcher",
    "TPESearcher",
    "TuneBOHB",
    "loguniform",
    "randint",
    "report",
    "sample_from",
    "uniform",
]


from ray_tpu._private.usage_stats import record_library_usage as _rlu

_rlu("tune")
del _rlu
