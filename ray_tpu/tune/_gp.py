"""Shared numpy RBF-GP UCB selection — the one regressor behind PB2's
explore step and BayesOptSearcher's acquisition (reference wraps GPy /
bayesian-optimization respectively; population sizes of tens of points
don't need more)."""
from __future__ import annotations

import numpy as np


def gp_ucb_select(X, y, cand, *, ls: float = 0.3, noise: float = 1e-3,
                  kappa: float = 1.0) -> np.ndarray:
    """Fit an RBF GP on (X, y) (inputs in the unit cube) and return the
    candidate row maximizing mean + kappa * std."""
    X = np.asarray(X, np.float64)
    y = np.asarray(y, np.float64)
    cand = np.asarray(cand, np.float64)
    y_mean, y_std = y.mean(), y.std() or 1.0
    yn = (y - y_mean) / y_std

    def rbf(a, b):
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-d2 / (2 * ls * ls))

    K = rbf(X, X) + noise * np.eye(len(X))
    Ks = rbf(cand, X)
    alpha = np.linalg.solve(K, yn)
    mu = Ks @ alpha
    v = np.linalg.solve(K, Ks.T)
    var = np.clip(1.0 - (Ks * v.T).sum(-1), 1e-9, None)
    ucb = mu + kappa * np.sqrt(var)
    return cand[int(np.argmax(ucb))]
