"""Tuner: the user-facing experiment entry point.

Equivalent of the reference's Tuner/ResultGrid (reference: python/ray/tune/
tuner.py:59 Tuner, tune.py:293 tune.run, result_grid.py ResultGrid).
``Tuner.restore(path, trainable)`` resumes an interrupted experiment from
its persisted state (reference: tune/execution/experiment_state.py).
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search import BasicVariantGenerator, Searcher
from ray_tpu.tune.trial import ERROR, TERMINATED, Trial
from ray_tpu.tune.tune_controller import TuneController


@dataclass
class TuneConfig:
    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: int | None = None
    search_alg: Searcher | None = None
    scheduler: TrialScheduler | None = None
    seed: int | None = None


@dataclass
class TuneRunConfig:
    name: str = ""
    storage_path: str = "~/ray_tpu_results"
    max_failures: int = 0


class TuneResult:
    def __init__(self, trial: Trial, metric: str, mode: str):
        self.trial = trial
        self.config = trial.config
        self.metrics = trial.last_result or {}
        self.error = trial.error
        self.checkpoint = None
        if trial.checkpoint_path:
            from ray_tpu.train.checkpoint import Checkpoint

            self.checkpoint = Checkpoint(trial.checkpoint_path)
        self._metric, self._mode = metric, mode

    @property
    def metrics_dataframe(self):
        import pandas as pd

        return pd.DataFrame(self.trial.results)


class ResultGrid:
    def __init__(self, trials: list[Trial], metric: str, mode: str):
        self._trials = trials
        self._metric, self._mode = metric, mode
        self._results = [TuneResult(t, metric, mode) for t in trials]

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> TuneResult:
        return self._results[i]

    @property
    def errors(self) -> list[str]:
        return [t.error for t in self._trials if t.status == ERROR]

    def get_best_result(self, metric: str | None = None,
                        mode: str | None = None) -> TuneResult:
        metric = metric or self._metric
        mode = mode or self._mode
        done = [r for r in self._results
                if r.metrics and metric in r.metrics]
        if not done:
            raise RuntimeError("no completed trials with metric " + metric)
        key = lambda r: r.metrics[metric]
        return max(done, key=key) if mode == "max" else min(done, key=key)

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame(
            [{**(t.last_result or {}), "trial_id": t.trial_id,
              "status": t.status, **{f"config/{k}": v
                                     for k, v in t.config.items()
                                     if not isinstance(v, dict)}}
             for t in self._trials]
        )


class Tuner:
    def __init__(
        self,
        trainable: Callable,
        *,
        param_space: dict | None = None,
        tune_config: TuneConfig | None = None,
        run_config: TuneRunConfig | None = None,
        _restore_from: str | None = None,
    ):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or TuneRunConfig()
        self._restore_from = _restore_from

    @classmethod
    def restore(cls, path: str, trainable: Callable,
                tune_config: TuneConfig | None = None,
                param_space: dict | None = None) -> "Tuner":
        """Resume an experiment from its persisted state. With `param_space`
        (and the original TuneConfig seed/num_samples) the search continues
        generating the not-yet-materialized samples; without it, only the
        already-created trials are finished."""
        return cls(trainable, tune_config=tune_config, param_space=param_space,
                   _restore_from=path)

    def _experiment_dir(self) -> str:
        if self._restore_from:
            return os.path.expanduser(self._restore_from)
        name = self.run_config.name or f"tune_{int(time.time())}"
        return os.path.join(os.path.expanduser(self.run_config.storage_path), name)

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        searcher = tc.search_alg or BasicVariantGenerator(
            self.param_space, num_samples=tc.num_samples, seed=tc.seed
        )
        searcher.set_search_properties(tc.metric, tc.mode)
        controller = TuneController(
            self.trainable,
            searcher=searcher,
            scheduler=tc.scheduler,
            metric=tc.metric,
            mode=tc.mode,
            experiment_dir=self._experiment_dir(),
            max_concurrent_trials=tc.max_concurrent_trials,
            max_failures=self.run_config.max_failures,
        )
        if self._restore_from:
            if not controller.load_state():
                raise FileNotFoundError(
                    f"no experiment state at {self._restore_from}"
                )
            if tc.search_alg is not None:
                # external searchers are stateful/stochastic: re-suggesting
                # for restored trials would pair fresh ask() configs with old
                # trials' results and corrupt the optimizer's history — only
                # finish the already-materialized trials
                controller._searcher_done = True
            elif self.param_space:
                # deterministic searcher (same param_space + seed): fast-forward
                # past the suggestions already materialized as trials, then keep
                # generating the remaining samples
                for t in controller.trials:
                    searcher.suggest(t.trial_id)
            else:
                controller._searcher_done = True
        trials = controller.run()
        return ResultGrid(trials, tc.metric, tc.mode)
