"""Search spaces + trial-config generators.

Equivalent of the reference's sample-space API and BasicVariantGenerator
(reference: python/ray/tune/search/sample.py — uniform/loguniform/choice/
randint/grid_search domains; python/ray/tune/search/basic_variant.py —
grid/random variant expansion). Custom searchers plug in via the Searcher
interface (reference: python/ray/tune/search/searcher.py).
"""
from __future__ import annotations

import itertools
import math
import random
from typing import Any, Callable, Iterator


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        if low <= 0 or high <= 0:
            raise ValueError("loguniform bounds must be positive")
        self.low, self.high = low, high

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


class Randint(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class SampleFrom(Domain):
    def __init__(self, fn: Callable[[dict], Any]):
        self.fn = fn

    def sample(self, rng):  # resolved against the spec later
        raise TypeError("SampleFrom is resolved with the config, not the rng")


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> Randint:
    return Randint(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def sample_from(fn: Callable[[dict], Any]) -> SampleFrom:
    return SampleFrom(fn)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def _split_spec(spec: dict, prefix=()) -> tuple[list, list]:
    """Walk the (possibly nested) param space → (grid_items, other_items)
    where each item is (key_path, domain_or_value)."""
    grids, others = [], []
    for k, v in spec.items():
        path = prefix + (k,)
        if isinstance(v, GridSearch):
            grids.append((path, v))
        elif isinstance(v, dict):
            g, o = _split_spec(v, path)
            grids.extend(g)
            others.extend(o)
        else:
            others.append((path, v))
    return grids, others


def _set_path(cfg: dict, path: tuple, value: Any) -> None:
    for k in path[:-1]:
        cfg = cfg.setdefault(k, {})
    cfg[path[-1]] = value


class Searcher:
    """Pluggable suggestion interface (reference: tune/search/searcher.py).
    Subclasses implement suggest() and optionally on_trial_complete()."""

    def set_search_properties(self, metric: str | None, mode: str | None) -> None:
        self.metric, self.mode = metric, mode

    def suggest(self, trial_id: str) -> dict | None:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: dict | None = None,
                          error: bool = False) -> None:
        pass


class SuggestAdapter(Searcher):
    """Bridge an EXTERNAL suggest/observe optimizer into tune — the
    Optuna/HyperOpt adapter pattern (reference:
    tune/search/optuna/optuna_search.py: `ask()` at suggest time, `tell()`
    at completion). The wrapped optimizer needs two methods:

        ask() -> dict | None            # next config (None = budget spent)
        tell(config, value) -> None     # observe an outcome; value is
                                        # normalized so HIGHER IS BETTER
                                        # (None for failed trials)

    max_trials bounds the sweep when the optimizer itself is unbounded.
    """

    def __init__(self, optimizer: Any, *, max_trials: int | None = None):
        self._opt = optimizer
        self._max_trials = max_trials
        self._suggested = 0
        self._live: dict[str, dict] = {}  # trial_id -> config
        self.metric: str | None = None
        self.mode: str | None = None

    def suggest(self, trial_id: str) -> dict | None:
        if self._max_trials is not None and self._suggested >= self._max_trials:
            return None
        cfg = self._opt.ask()
        if cfg is None:
            return None
        self._suggested += 1
        self._live[trial_id] = dict(cfg)
        return dict(cfg)

    def on_trial_complete(self, trial_id: str, result: dict | None = None,
                          error: bool = False) -> None:
        cfg = self._live.pop(trial_id, None)
        if cfg is None:
            return
        value = None
        if not error and result is not None and self.metric in result:
            value = float(result[self.metric])
            if self.mode == "min":
                value = -value  # adapter contract: higher is better
        try:
            self._opt.tell(cfg, value)
        except Exception:  # noqa: BLE001 — a broken external optimizer must
            pass  #                         not take down the experiment


class BasicVariantGenerator(Searcher):
    """Grid x random expansion: the cross-product of all grid_search values,
    repeated num_samples times with random domains re-sampled per repeat."""

    def __init__(self, param_space: dict, num_samples: int = 1, seed: int | None = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        self._iter = self._generate()

    def _generate(self) -> Iterator[dict]:
        grids, others = _split_spec(self.param_space)
        grid_paths = [p for p, _ in grids]
        grid_values = [g.values for _, g in grids]
        combos = list(itertools.product(*grid_values)) if grids else [()]
        for _ in range(self.num_samples):
            for combo in combos:
                cfg: dict = {}
                for path, val in zip(grid_paths, combo):
                    _set_path(cfg, path, val)
                deferred = []
                for path, v in others:
                    if isinstance(v, Domain):
                        if isinstance(v, SampleFrom):
                            deferred.append((path, v))
                        else:
                            _set_path(cfg, path, v.sample(self.rng))
                    else:
                        _set_path(cfg, path, v)
                for path, v in deferred:
                    _set_path(cfg, path, v.fn(cfg))
                yield cfg

    def suggest(self, trial_id: str) -> dict | None:
        return next(self._iter, None)
