"""Search spaces + trial-config generators.

Equivalent of the reference's sample-space API and BasicVariantGenerator
(reference: python/ray/tune/search/sample.py — uniform/loguniform/choice/
randint/grid_search domains; python/ray/tune/search/basic_variant.py —
grid/random variant expansion). Custom searchers plug in via the Searcher
interface (reference: python/ray/tune/search/searcher.py).
"""
from __future__ import annotations

import itertools
import math
import random
from typing import Any, Callable, Iterator


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        if low <= 0 or high <= 0:
            raise ValueError("loguniform bounds must be positive")
        self.low, self.high = low, high

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


class Randint(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Choice(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class SampleFrom(Domain):
    def __init__(self, fn: Callable[[dict], Any]):
        self.fn = fn

    def sample(self, rng):  # resolved against the spec later
        raise TypeError("SampleFrom is resolved with the config, not the rng")


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> Randint:
    return Randint(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def sample_from(fn: Callable[[dict], Any]) -> SampleFrom:
    return SampleFrom(fn)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def _split_spec(spec: dict, prefix=()) -> tuple[list, list]:
    """Walk the (possibly nested) param space → (grid_items, other_items)
    where each item is (key_path, domain_or_value)."""
    grids, others = [], []
    for k, v in spec.items():
        path = prefix + (k,)
        if isinstance(v, GridSearch):
            grids.append((path, v))
        elif isinstance(v, dict):
            g, o = _split_spec(v, path)
            grids.extend(g)
            others.extend(o)
        else:
            others.append((path, v))
    return grids, others


def _set_path(cfg: dict, path: tuple, value: Any) -> None:
    for k in path[:-1]:
        cfg = cfg.setdefault(k, {})
    cfg[path[-1]] = value


class Searcher:
    """Pluggable suggestion interface (reference: tune/search/searcher.py).
    Subclasses implement suggest() and optionally on_trial_complete()."""

    def set_search_properties(self, metric: str | None, mode: str | None) -> None:
        self.metric, self.mode = metric, mode

    def suggest(self, trial_id: str) -> dict | None:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: dict | None = None,
                          error: bool = False) -> None:
        pass


class SuggestAdapter(Searcher):
    """Bridge an EXTERNAL suggest/observe optimizer into tune — the
    Optuna/HyperOpt adapter pattern (reference:
    tune/search/optuna/optuna_search.py: `ask()` at suggest time, `tell()`
    at completion). The wrapped optimizer needs two methods:

        ask() -> dict | None            # next config (None = budget spent)
        tell(config, value) -> None     # observe an outcome; value is
                                        # normalized so HIGHER IS BETTER
                                        # (None for failed trials)

    max_trials bounds the sweep when the optimizer itself is unbounded.
    """

    def __init__(self, optimizer: Any, *, max_trials: int | None = None):
        self._opt = optimizer
        self._max_trials = max_trials
        self._suggested = 0
        self._live: dict[str, dict] = {}  # trial_id -> config
        self.metric: str | None = None
        self.mode: str | None = None

    def suggest(self, trial_id: str) -> dict | None:
        if self._max_trials is not None and self._suggested >= self._max_trials:
            return None
        cfg = self._opt.ask()
        if cfg is None:
            return None
        self._suggested += 1
        self._live[trial_id] = dict(cfg)
        return dict(cfg)

    def on_trial_complete(self, trial_id: str, result: dict | None = None,
                          error: bool = False) -> None:
        cfg = self._live.pop(trial_id, None)
        if cfg is None:
            return
        value = None
        if not error and result is not None and self.metric in result:
            value = float(result[self.metric])
            if self.mode == "min":
                value = -value  # adapter contract: higher is better
        try:
            self._opt.tell(cfg, value)
        except Exception:  # noqa: BLE001 — a broken external optimizer must
            pass  #                         not take down the experiment


def _partition_space(param_space: dict, searcher_name: str,
                     allow_choice: bool = True):
    """(dims, fixed, deferred) split shared by the model-based searchers;
    grid domains are rejected uniformly."""
    grids, others = _split_spec(param_space)
    if grids:
        raise ValueError(f"{searcher_name} does not accept grid_search "
                         "domains; use BasicVariantGenerator")
    dims, fixed, deferred = [], [], []
    for path, v in others:
        if not allow_choice and isinstance(v, Choice):
            raise ValueError(
                f"{searcher_name} models numeric domains only (reference "
                "bayesopt has the same limit); use TPESearcher for "
                "categorical spaces")
        if isinstance(v, SampleFrom):
            deferred.append((path, v))
        elif isinstance(v, Domain):
            dims.append((path, v))
        else:
            fixed.append((path, v))
    return dims, fixed, deferred


def _assemble_config(fixed, deferred, dim_values) -> dict:
    """fixed + modeled dim values + deferred sample_from (which may read
    the already-set keys), in that order."""
    cfg: dict = {}
    for path, v in fixed:
        _set_path(cfg, path, v)
    for path, v in dim_values:
        _set_path(cfg, path, v)
    for path, v in deferred:
        _set_path(cfg, path, v.fn(cfg))
    return cfg


class TPESearcher(Searcher):
    """Native Tree-structured Parzen Estimator searcher (Bergstra et al.
    2011) — the model behind Optuna's default sampler and HyperOpt
    (reference integrates those externally via tune/search/optuna/,
    tune/search/hyperopt/; this is an in-tree implementation with no
    dependency, pluggable exactly like them).

    Observations are split at the gamma-quantile into good/bad sets; each
    numeric dimension gets a Parzen (Gaussian-mixture) density per set, and
    candidates drawn from the good density are ranked by the likelihood
    ratio l(x)/g(x). Categorical dims use add-one-smoothed frequencies.
    Until n_startup completions it falls back to random sampling.

    Compose with ASHA for BOHB-style search (model-based suggestions +
    successive-halving early stopping): Tuner(tune_config=TuneConfig(
    searcher=TPESearcher(...), scheduler=ASHAScheduler(...))).
    """

    def __init__(self, param_space: dict, *, metric: str | None = None,
                 mode: str | None = None, n_startup: int = 10,
                 gamma: float = 0.25, n_candidates: int = 24,
                 max_trials: int | None = None, seed: int | None = None):
        self._dims, self._fixed, self._deferred = _partition_space(
            param_space, "TPESearcher")
        self.metric, self.mode = metric, mode
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._max_trials = max_trials
        self._suggested = 0
        self.rng = random.Random(seed)
        self._live: dict[str, dict] = {}
        self._obs: list[tuple[dict, float]] = []  # (flat values, score)

    def set_search_properties(self, metric, mode):
        if self.metric is None:
            self.metric = metric
        if self.mode is None:
            self.mode = mode

    # -- per-dimension densities ------------------------------------------

    @staticmethod
    def _to_unit(domain, x: float) -> float:
        if isinstance(domain, LogUniform):
            lo, hi = math.log(domain.low), math.log(domain.high)
            return (math.log(x) - lo) / (hi - lo)
        return (x - domain.low) / (domain.high - domain.low)

    @staticmethod
    def _from_unit(domain, u: float):
        u = min(max(u, 0.0), 1.0)
        if isinstance(domain, LogUniform):
            lo, hi = math.log(domain.low), math.log(domain.high)
            return math.exp(lo + u * (hi - lo))
        x = domain.low + u * (domain.high - domain.low)
        if isinstance(domain, Randint):
            return min(int(x), domain.high - 1)
        return x

    def _parzen(self, units: list[float]):
        """(centers, bandwidth) in unit space; uniform prior as an extra
        pseudo-center keeps exploration alive."""
        n = len(units)
        bw = max(1.0 / (1 + n) ** 0.5 * 0.5, 0.05)
        return units, bw

    def _sample_parzen(self, centers, bw) -> float:
        c = self.rng.choice(centers) if centers else self.rng.random()
        return self.rng.gauss(c, bw)

    @staticmethod
    def _parzen_pdf(u: float, centers, bw) -> float:
        # mixture of gaussians + a uniform component (weight 1/(n+1))
        n = len(centers)
        if n == 0:
            return 1.0
        s = 0.0
        for c in centers:
            s += math.exp(-0.5 * ((u - c) / bw) ** 2) / (bw * 2.5066282746)
        return (s + 1.0) / (n + 1)

    # -- suggest/observe ---------------------------------------------------

    def _random_config(self) -> dict:
        flat = {path: d.sample(self.rng) for path, d in self._dims}
        return flat

    def _tpe_config(self) -> dict:
        scored = sorted(self._obs, key=lambda o: -o[1])
        n_good = max(1, int(self.gamma * len(scored)))
        good, bad = scored[:n_good], scored[n_good:]
        flat: dict = {}
        for path, d in self._dims:
            if isinstance(d, Choice):
                k = len(d.categories)
                def probs(obs):
                    counts = [1.0] * k
                    for cfg, _ in obs:
                        counts[d.categories.index(cfg[path])] += 1.0
                    t = sum(counts)
                    return [c / t for c in counts]
                pg, pb = probs(good), probs(bad)
                best_i = max(
                    range(k),
                    key=lambda i: (pg[i] / pb[i]) if pb[i] > 0 else pg[i],
                )
                # sample from good-probabilities but biased to the best ratio
                if self.rng.random() < 0.8:
                    flat[path] = d.categories[best_i]
                else:
                    r, acc = self.rng.random(), 0.0
                    for i, p in enumerate(pg):
                        acc += p
                        if r <= acc:
                            flat[path] = d.categories[i]
                            break
                    else:
                        flat[path] = d.categories[-1]
                continue
            gu = [self._to_unit(d, cfg[path]) for cfg, _ in good]
            bu = [self._to_unit(d, cfg[path]) for cfg, _ in bad]
            gc, gbw = self._parzen(gu)
            bc, bbw = self._parzen(bu)
            best_u, best_ratio = None, -1.0
            for _ in range(self.n_candidates):
                u = self._sample_parzen(gc, gbw)
                ratio = (self._parzen_pdf(u, gc, gbw)
                         / max(self._parzen_pdf(u, bc, bbw), 1e-12))
                if ratio > best_ratio:
                    best_u, best_ratio = u, ratio
            flat[path] = self._from_unit(d, best_u)
        return flat

    def suggest(self, trial_id: str) -> dict | None:
        if self._max_trials is not None and self._suggested >= self._max_trials:
            return None
        self._suggested += 1
        if len(self._obs) < self.n_startup:
            flat = self._random_config()
        else:
            flat = self._tpe_config()
        cfg = _assemble_config(self._fixed, self._deferred, flat.items())
        self._live[trial_id] = flat
        return cfg

    def on_trial_complete(self, trial_id: str, result: dict | None = None,
                          error: bool = False) -> None:
        flat = self._live.pop(trial_id, None)
        if flat is None or error or result is None or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._obs.append((flat, score))


class TuneBOHB(TPESearcher):
    """BOHB's model-based half (Falkner et al. 2018; reference:
    tune/search/bohb/bohb_search.py TuneBOHB). A TPE model fit
    PER BUDGET: milestone results reported by HyperBandForBOHB land in
    per-budget observation pools, and suggestions are drawn from the model
    of the LARGEST budget that has at least n_startup observations —
    BOHB's defining rule, so early low-budget evidence guides the search
    immediately but is superseded by higher-fidelity evidence as brackets
    deepen. Pair with schedulers.HyperBandForBOHB, which feeds
    on_budget_result at every milestone barrier."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._budget_obs: dict[float, list[tuple[dict, float]]] = {}

    def on_budget_result(self, trial_id: str, budget: float,
                         result: dict) -> None:
        flat = self._live.get(trial_id)
        if flat is None or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._budget_obs.setdefault(float(budget), []).append((flat, score))

    def _select_pool(self) -> list[tuple[dict, float]]:
        # completions ran to max_t — the HIGHEST fidelity pool of all, so
        # it is consulted first, then milestone pools in descending budget
        if len(self._obs) >= self.n_startup:
            return self._obs
        for budget in sorted(self._budget_obs, reverse=True):
            pool = self._budget_obs[budget]
            if len(pool) >= self.n_startup:
                return pool
        return self._obs

    def suggest(self, trial_id: str) -> dict | None:
        pool = self._select_pool()
        # swap the pool the parent's model fits on for this suggestion
        saved, self._obs = self._obs, pool
        try:
            return super().suggest(trial_id)
        finally:
            self._obs = saved


class BayesOptSearcher(Searcher):
    """Native GP-UCB Bayesian optimization (reference:
    tune/search/bayesopt/bayesopt_search.py, which wraps the external
    `bayesian-optimization` package; this is an in-tree numpy RBF-GP —
    the same regressor PB2 uses — with an upper-confidence-bound
    acquisition over unit-cube candidates). Numeric domains only, like
    the reference (categoricals want TPESearcher)."""

    def __init__(self, param_space: dict, *, metric: str | None = None,
                 mode: str | None = None, n_startup: int = 5,
                 kappa: float = 2.0, n_candidates: int = 256,
                 max_trials: int | None = None, seed: int | None = None):
        self._dims, self._fixed, self._deferred = _partition_space(
            param_space, "BayesOptSearcher", allow_choice=False)
        self.metric, self.mode = metric, mode
        self.n_startup = n_startup
        self.kappa = kappa
        self.n_candidates = n_candidates
        self._max_trials = max_trials
        self._suggested = 0
        self.rng = random.Random(seed)
        self._live: dict[str, dict] = {}   # trial_id -> unit coords
        self._obs: list[tuple[list[float], float]] = []

    def set_search_properties(self, metric, mode):
        if self.metric is None:
            self.metric = metric
        if self.mode is None:
            self.mode = mode

    def _acquire(self) -> list[float]:
        import numpy as np

        from ray_tpu.tune._gp import gp_ucb_select

        d = len(self._dims)
        cand = np.array([[self.rng.random() for _ in range(d)]
                         for _ in range(self.n_candidates)])
        best = gp_ucb_select([u for u, _ in self._obs],
                             [s for _, s in self._obs], cand,
                             ls=0.2, noise=1e-4, kappa=self.kappa)
        return [float(u) for u in best]

    def suggest(self, trial_id: str) -> dict | None:
        if self._max_trials is not None and self._suggested >= self._max_trials:
            return None
        self._suggested += 1
        if len(self._obs) < self.n_startup:
            units = [self.rng.random() for _ in self._dims]
        else:
            units = self._acquire()
        cfg = _assemble_config(
            self._fixed, self._deferred,
            [(path, TPESearcher._from_unit(dom, u))
             for (path, dom), u in zip(self._dims, units)])
        self._live[trial_id] = units
        return cfg

    def on_trial_complete(self, trial_id: str, result: dict | None = None,
                          error: bool = False) -> None:
        units = self._live.pop(trial_id, None)
        if units is None or error or result is None or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._obs.append((units, score))


class BasicVariantGenerator(Searcher):
    """Grid x random expansion: the cross-product of all grid_search values,
    repeated num_samples times with random domains re-sampled per repeat."""

    def __init__(self, param_space: dict, num_samples: int = 1, seed: int | None = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        self._iter = self._generate()

    def _generate(self) -> Iterator[dict]:
        grids, others = _split_spec(self.param_space)
        grid_paths = [p for p, _ in grids]
        grid_values = [g.values for _, g in grids]
        combos = list(itertools.product(*grid_values)) if grids else [()]
        for _ in range(self.num_samples):
            for combo in combos:
                cfg: dict = {}
                for path, val in zip(grid_paths, combo):
                    _set_path(cfg, path, val)
                deferred = []
                for path, v in others:
                    if isinstance(v, Domain):
                        if isinstance(v, SampleFrom):
                            deferred.append((path, v))
                        else:
                            _set_path(cfg, path, v.sample(self.rng))
                    else:
                        _set_path(cfg, path, v)
                for path, v in deferred:
                    _set_path(cfg, path, v.fn(cfg))
                yield cfg

    def suggest(self, trial_id: str) -> dict | None:
        return next(self._iter, None)
