"""Trial schedulers: FIFO, ASHA, median-stopping, PBT.

Equivalent of the reference's scheduler suite (reference: python/ray/tune/
schedulers/ — ASHA async_hyperband.py:19, PBT pbt.py:222, median stopping
median_stopping_rule.py). Schedulers see every reported result and return
CONTINUE/STOP; PBT additionally rewrites config + restore checkpoint on
exploit.
"""
from __future__ import annotations

import math
import random
from typing import Dict, List, Optional

from ray_tpu.tune.trial import Trial

CONTINUE = "CONTINUE"
STOP = "STOP"
# the trial is checkpointed and parked; the scheduler releases it later via
# pending_actions() (synchronous band semantics need trials to WAIT)
PAUSE = "PAUSE"


class TrialScheduler:
    def set_search_properties(self, metric: str, mode: str) -> None:
        self.metric, self.mode = metric, mode

    def _score(self, result: dict) -> float:
        v = float(result[self.metric])
        if math.isnan(v):
            return -math.inf  # diverged trials rank worst in either mode
        return v if self.mode == "max" else -v

    def on_trial_add(self, trial: Trial) -> None:
        """Called when the controller creates the trial — BEFORE its first
        report. Synchronous schedulers need the full population to know
        when a barrier is complete."""

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial: Trial) -> None:
        pass

    def pending_actions(self) -> Dict[str, str]:
        """trial_id -> "RESUME" | "STOP" for trials the scheduler parked
        with PAUSE; drained by the controller once per step. Base: none."""
        return {}


class FIFOScheduler(TrialScheduler):
    pass


class _Bracket:
    """One ASHA rung ladder: milestones at grace * rf**k, each rung records
    one score per trial (its score when it first reaches the rung)."""

    def __init__(self, grace_period: float, rf: float, max_t: float):
        # rung milestone -> {trial_id: score at crossing}
        self.rungs: Dict[float, Dict[str, float]] = {}
        m = grace_period
        while m < max_t:
            self.rungs[m] = {}
            m = m * rf

    def on_result(self, trial_id: str, t: float, score: float, rf: float) -> str:
        for milestone in sorted(self.rungs, reverse=True):
            if t < milestone:
                continue
            recorded = self.rungs[milestone]
            # record the score seen when this trial first crosses the rung;
            # all judging uses these crossing scores so every comparison is
            # at the same t (current-report scores are at incomparable t)
            recorded.setdefault(trial_id, score)
            # re-judged on EVERY report while this is the trial's highest
            # rung: a trial that crossed an empty rung gets re-checked once
            # peers arrive, so rung order doesn't decide survival
            if len(recorded) >= 2:
                # cutoff = top 1/rf quantile of per-trial crossing scores;
                # own score is included, so the rung's best can never stop
                vals = sorted(recorded.values(), reverse=True)
                cutoff = vals[max(0, int(len(vals) / rf) - 1)]
                if recorded[trial_id] < cutoff:
                    return STOP
            return CONTINUE
        return CONTINUE


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: tune/schedulers/async_hyperband.py:19): rungs at
    grace_period * reduction_factor**k; each trial's score is frozen when it
    first crosses a rung, and while that rung is the trial's highest it is
    re-judged on every report: it stops as soon as its frozen crossing score
    falls out of the top 1/reduction_factor of all scores recorded at the
    rung (so a trial that crossed an empty rung can be stopped later, once
    enough peers arrive). Multiple brackets stagger grace periods (bracket s
    starts at grace * rf**s); trials are assigned round-robin."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: float = 3,
                 max_t: int = 100, brackets: int = 1):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        self._brackets = [
            _Bracket(grace_period * reduction_factor ** s, reduction_factor, max_t)
            for s in range(max(1, brackets))
        ]
        self._trial_bracket: Dict[str, _Bracket] = {}

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        t = result.get(self.time_attr, trial.iteration)
        if t >= self.max_t:
            return STOP
        bracket = self._trial_bracket.setdefault(
            trial.trial_id,
            self._brackets[len(self._trial_bracket) % len(self._brackets)],
        )
        return bracket.on_result(trial.trial_id, t, self._score(result), self.rf)


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best score is below the median of the running
    averages of completed results (reference: tune/schedulers/
    median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = {}

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        scores = self._avgs.setdefault(trial.trial_id, [])
        scores.append(self._score(result))
        t = result.get(self.time_attr, trial.iteration)
        if t < self.grace_period:
            return CONTINUE
        others = [sum(v) / len(v) for k, v in self._avgs.items()
                  if k != trial.trial_id and v]
        if len(others) < self.min_samples:
            return CONTINUE
        median = sorted(others)[len(others) // 2]
        best = max(scores)
        return STOP if best < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py:222): every
    perturbation_interval, bottom-quantile trials clone the checkpoint of a
    top-quantile trial (exploit) and perturb its hyperparameters (explore).
    The controller applies the returned decision by restarting the trial
    actor with trial.config / trial.restore_path updated in place."""

    EXPLOIT = "EXPLOIT"  # internal decision: controller restarts the trial

    def __init__(self, time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: dict | None = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: int | None = None,
                 policy_log_dir: str | None = None):
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self.rng = random.Random(seed)
        # exploit decisions append (t, config) per trial here, replayable
        # by PopulationBasedTrainingReplay (reference: pbt.py policy logs)
        self.policy_log_dir = policy_log_dir
        self._last_perturb: Dict[str, float] = {}
        # trial_id -> (score, checkpoint_path, config) at last report
        self._state: Dict[str, tuple] = {}

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        score = self._score(result)
        self._state[trial.trial_id] = (score, trial.checkpoint_path, dict(trial.config))
        t = result.get(self.time_attr, trial.iteration)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval:
            return CONTINUE

        ranked = sorted(self._state.items(), key=lambda kv: kv[1][0])
        n = len(ranked)
        if n < 2:
            # no peer has reported yet (e.g. its actor is still spawning):
            # leave the boundary armed instead of consuming it, so the
            # comparison happens as soon as a peer shows up
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        k = max(1, int(n * self.quantile))
        bottom = [tid for tid, _ in ranked[:k]]
        top = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id not in bottom or trial.trial_id in top:
            return CONTINUE
        donor_id = self.rng.choice(top)
        donor_score, donor_ckpt, donor_cfg = self._state[donor_id]
        if donor_ckpt is None:
            return CONTINUE
        trial.config = self._explore(donor_cfg)
        trial.restore_path = donor_ckpt
        self._log_policy(trial.trial_id, t, trial.config)
        return self.EXPLOIT

    def _log_policy(self, trial_id: str, t: float, config: dict) -> None:
        if not self.policy_log_dir:
            return
        import json as _json
        import os as _os

        _os.makedirs(self.policy_log_dir, exist_ok=True)
        path = _os.path.join(self.policy_log_dir,
                             f"pbt_policy_{trial_id}.jsonl")
        with open(path, "a") as f:
            f.write(_json.dumps({"t": t, "config": config}) + "\n")

    def _explore(self, config: dict) -> dict:
        new = dict(config)
        for key, spec in self.mutations.items():
            if key not in new:
                continue
            if callable(spec):
                new[key] = spec()
            elif isinstance(spec, list):
                if self.rng.random() < self.resample_prob or new[key] not in spec:
                    new[key] = self.rng.choice(spec)
                else:
                    i = spec.index(new[key])
                    i = min(len(spec) - 1, max(0, i + self.rng.choice([-1, 1])))
                    new[key] = spec[i]
            elif isinstance(spec, dict) and "lower" in spec:
                lo, hi = spec["lower"], spec["upper"]
                if self.rng.random() < self.resample_prob:
                    new[key] = self.rng.uniform(lo, hi)
                else:
                    new[key] = min(hi, max(lo, new[key] * self.rng.choice([0.8, 1.2])))
        return new


class HyperBandScheduler(TrialScheduler):
    """SYNCHRONOUS HyperBand (reference: tune/schedulers/hyperband.py:42
    HyperBandScheduler — distinct from ASHA: successive-halving cuts happen
    at a barrier). All live trials run to the current band milestone; a
    trial that reaches it early is PAUSED (checkpointed + parked) until
    every peer arrives, then the band keeps the top 1/reduction_factor by
    milestone score, STOPs the rest, and resumes survivors toward the next
    milestone (x reduction_factor). The barrier trades the stragglers'
    wall-clock for exact same-budget comparisons — ASHA's frozen crossing
    scores approximate this without waiting.
    """

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: float = 3,
                 max_t: int = 81):
        self.time_attr = time_attr
        self.rf = reduction_factor
        self.max_t = max_t
        self.milestone = float(grace_period)
        self._scores: Dict[str, float] = {}  # tid -> score AT the milestone
        self._live: set[str] = set()
        self._paused: set[str] = set()
        self._actions: Dict[str, str] = {}

    def on_trial_add(self, trial: Trial) -> None:
        # membership registers at trial CREATION so the first reporter
        # can't trigger a solo "barrier" before peers ever report
        self._live.add(trial.trial_id)

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        tid = trial.trial_id
        self._live.add(tid)
        t = result.get(self.time_attr, trial.iteration)
        if t >= self.max_t:
            # fully retire the trial: a stale _scores/_paused entry would
            # let a dead trial occupy a keep slot at the next barrier cut
            self._live.discard(tid)
            self._scores.pop(tid, None)
            self._paused.discard(tid)
            self._maybe_cut()
            return STOP
        if t < self.milestone:
            return CONTINUE
        self._scores.setdefault(tid, self._score(result))
        if self._maybe_cut():
            # the band just cut; this trial's own fate is in _actions
            verdict = self._actions.pop(tid, "RESUME")
            return STOP if verdict == "STOP" else CONTINUE
        if trial.checkpoint_path is None:
            # a pause would restart this trial from scratch (nothing to
            # restore); keep it running — its milestone score is already
            # frozen, so the barrier semantics are preserved
            return CONTINUE
        self._paused.add(tid)
        return PAUSE

    def on_trial_complete(self, trial: Trial) -> None:
        self._live.discard(trial.trial_id)
        self._scores.pop(trial.trial_id, None)
        self._paused.discard(trial.trial_id)
        self._maybe_cut()

    def _maybe_cut(self) -> bool:
        """When every live trial has a recorded score at the current
        milestone, run the successive-halving cut."""
        waiting = self._live - set(self._scores)
        if waiting or not self._scores:
            return False
        ranked = sorted(self._scores.items(), key=lambda kv: -kv[1])
        keep = max(1, int(math.ceil(len(ranked) / self.rf)))
        for i, (tid, _score) in enumerate(ranked):
            verdict = "RESUME" if i < keep else "STOP"
            if tid in self._paused:
                self._paused.discard(tid)
                self._actions[tid] = verdict
            else:
                # the trial that triggered the cut is still running; its
                # verdict is consumed by on_trial_result's return
                self._actions[tid] = verdict
            if verdict == "STOP":
                self._live.discard(tid)
        self._scores.clear()
        self.milestone *= self.rf
        return True

    def pending_actions(self) -> Dict[str, str]:
        out = {tid: v for tid, v in self._actions.items()}
        self._actions.clear()
        return out


class HyperBandForBOHB(HyperBandScheduler):
    """HyperBand variant that feeds every milestone observation to a
    linked TuneBOHB searcher (reference: tune/schedulers/hb_bohb.py
    HyperBandForBOHB). The scheduler side of BOHB is unchanged
    synchronous successive halving; the coupling is that each trial's
    score AT a budget barrier becomes a per-budget training point for the
    searcher's TPE model, so later suggestions are model-based at the
    highest fidelity that has enough evidence."""

    def __init__(self, *args, searcher=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._searcher = searcher

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        tid = trial.trial_id
        t = result.get(self.time_attr, trial.iteration)
        if (self._searcher is not None and t >= self.milestone
                and t < self.max_t and tid not in self._scores):
            # the t >= max_t retire path never records a milestone score,
            # so feeding it here would mislabel a full-budget observation
            # with the current (lower) barrier's budget
            # first report at/after the current barrier: this is the score
            # HyperBand will judge at budget=milestone — tell the model
            self._searcher.on_budget_result(tid, self.milestone, result)
        return super().on_trial_result(trial, result)


class PB2(PopulationBasedTraining):
    """Population-Based Bandits (reference: tune/schedulers/pb2.py —
    PBT whose EXPLORE step replaces random perturbation with a GP-bandit
    suggestion: fit a Gaussian process on (hyperparams -> score
    improvement) observations and pick the UCB-maximizing candidate within
    `hyperparam_bounds`). The GP here is a plain numpy RBF regressor — the
    reference wraps GPy; the population sizes involved (tens of points)
    don't need more.
    """

    def __init__(self, time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_bounds: dict | None = None,
                 quantile_fraction: float = 0.25,
                 ucb_kappa: float = 1.0,
                 n_candidates: int = 64,
                 seed: int | None = None):
        super().__init__(
            time_attr=time_attr,
            perturbation_interval=perturbation_interval,
            hyperparam_mutations={},  # explore is GP-driven, not mutation
            quantile_fraction=quantile_fraction,
            seed=seed,
        )
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds={key: [lo, hi]}")
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.kappa = ucb_kappa
        self.n_candidates = n_candidates
        # observations: (config vector, score delta over one interval)
        self._obs_x: List[List[float]] = []
        self._obs_y: List[float] = []
        self._prev_score: Dict[str, float] = {}

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        # record the improvement observation BEFORE the PBT boundary logic
        score = self._score(result)
        t = result.get(self.time_attr, trial.iteration)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last >= self.interval:
            prev = self._prev_score.get(trial.trial_id)
            if prev is not None:
                self._obs_x.append(self._vec(trial.config))
                self._obs_y.append(score - prev)
            self._prev_score[trial.trial_id] = score
        return super().on_trial_result(trial, result)

    def _vec(self, config: dict) -> List[float]:
        out = []
        for k, (lo, hi) in sorted(self.bounds.items()):
            v = float(config.get(k, lo))
            out.append((v - lo) / (hi - lo) if hi > lo else 0.0)
        return out

    def _explore(self, config: dict) -> dict:
        import numpy as np

        new = dict(config)
        keys = sorted(self.bounds)
        cand = np.array([
            [self.rng.random() for _ in keys]
            for _ in range(self.n_candidates)
        ])
        if len(self._obs_y) >= 3:
            from ray_tpu.tune._gp import gp_ucb_select

            best = gp_ucb_select(self._obs_x, self._obs_y, cand,
                                 kappa=self.kappa)
        else:
            best = cand[0]  # cold start: random draw inside the bounds
        for k, u in zip(keys, best):
            lo, hi = self.bounds[k]
            new[k] = lo + float(u) * (hi - lo)
        return new


class PopulationBasedTrainingReplay(TrialScheduler):
    """Replay a recorded PBT schedule on a SINGLE trial (reference:
    tune/schedulers/pbt.py:1035 PopulationBasedTrainingReplay): the policy
    log written by PopulationBasedTraining(policy_log_dir=...) lists
    (t, config) switch points; the replay applies each config at its
    recorded time, restoring from the trial's own checkpoint — re-training
    the winning lineage without re-running the population."""

    def __init__(self, policy_log: str):
        import json as _json

        self.schedule: List[tuple] = []
        with open(policy_log) as f:
            for line in f:
                line = line.strip()
                if line:
                    rec = _json.loads(line)
                    self.schedule.append((float(rec["t"]), rec["config"]))
        self.schedule.sort(key=lambda x: x[0])
        self._next = 0

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        t = result.get("training_iteration", trial.iteration)
        if self._next < len(self.schedule) and t >= self.schedule[self._next][0]:
            _t, config = self.schedule[self._next]
            self._next += 1
            trial.config = dict(config)
            trial.restore_path = trial.checkpoint_path  # own lineage
            return PopulationBasedTraining.EXPLOIT
        return CONTINUE


# Public alias matching the reference's preferred name (reference:
# tune/schedulers/__init__.py exports ASHAScheduler = AsyncHyperBandScheduler)
ASHAScheduler = AsyncHyperBandScheduler
