"""Trial schedulers: FIFO, ASHA, median-stopping, PBT.

Equivalent of the reference's scheduler suite (reference: python/ray/tune/
schedulers/ — ASHA async_hyperband.py:19, PBT pbt.py:222, median stopping
median_stopping_rule.py). Schedulers see every reported result and return
CONTINUE/STOP; PBT additionally rewrites config + restore checkpoint on
exploit.
"""
from __future__ import annotations

import math
import random
from typing import Dict, List, Optional

from ray_tpu.tune.trial import Trial

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def set_search_properties(self, metric: str, mode: str) -> None:
        self.metric, self.mode = metric, mode

    def _score(self, result: dict) -> float:
        v = float(result[self.metric])
        if math.isnan(v):
            return -math.inf  # diverged trials rank worst in either mode
        return v if self.mode == "max" else -v

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial: Trial) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    pass


class _Bracket:
    """One ASHA rung ladder: milestones at grace * rf**k, each rung records
    one score per trial (its score when it first reaches the rung)."""

    def __init__(self, grace_period: float, rf: float, max_t: float):
        # rung milestone -> {trial_id: score at crossing}
        self.rungs: Dict[float, Dict[str, float]] = {}
        m = grace_period
        while m < max_t:
            self.rungs[m] = {}
            m = m * rf

    def on_result(self, trial_id: str, t: float, score: float, rf: float) -> str:
        for milestone in sorted(self.rungs, reverse=True):
            if t < milestone:
                continue
            recorded = self.rungs[milestone]
            # record the score seen when this trial first crosses the rung;
            # all judging uses these crossing scores so every comparison is
            # at the same t (current-report scores are at incomparable t)
            recorded.setdefault(trial_id, score)
            # re-judged on EVERY report while this is the trial's highest
            # rung: a trial that crossed an empty rung gets re-checked once
            # peers arrive, so rung order doesn't decide survival
            if len(recorded) >= 2:
                # cutoff = top 1/rf quantile of per-trial crossing scores;
                # own score is included, so the rung's best can never stop
                vals = sorted(recorded.values(), reverse=True)
                cutoff = vals[max(0, int(len(vals) / rf) - 1)]
                if recorded[trial_id] < cutoff:
                    return STOP
            return CONTINUE
        return CONTINUE


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: tune/schedulers/async_hyperband.py:19): rungs at
    grace_period * reduction_factor**k; each trial's score is frozen when it
    first crosses a rung, and while that rung is the trial's highest it is
    re-judged on every report: it stops as soon as its frozen crossing score
    falls out of the top 1/reduction_factor of all scores recorded at the
    rung (so a trial that crossed an empty rung can be stopped later, once
    enough peers arrive). Multiple brackets stagger grace periods (bracket s
    starts at grace * rf**s); trials are assigned round-robin."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: float = 3,
                 max_t: int = 100, brackets: int = 1):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        self._brackets = [
            _Bracket(grace_period * reduction_factor ** s, reduction_factor, max_t)
            for s in range(max(1, brackets))
        ]
        self._trial_bracket: Dict[str, _Bracket] = {}

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        t = result.get(self.time_attr, trial.iteration)
        if t >= self.max_t:
            return STOP
        bracket = self._trial_bracket.setdefault(
            trial.trial_id,
            self._brackets[len(self._trial_bracket) % len(self._brackets)],
        )
        return bracket.on_result(trial.trial_id, t, self._score(result), self.rf)


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best score is below the median of the running
    averages of completed results (reference: tune/schedulers/
    median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = {}

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        scores = self._avgs.setdefault(trial.trial_id, [])
        scores.append(self._score(result))
        t = result.get(self.time_attr, trial.iteration)
        if t < self.grace_period:
            return CONTINUE
        others = [sum(v) / len(v) for k, v in self._avgs.items()
                  if k != trial.trial_id and v]
        if len(others) < self.min_samples:
            return CONTINUE
        median = sorted(others)[len(others) // 2]
        best = max(scores)
        return STOP if best < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py:222): every
    perturbation_interval, bottom-quantile trials clone the checkpoint of a
    top-quantile trial (exploit) and perturb its hyperparameters (explore).
    The controller applies the returned decision by restarting the trial
    actor with trial.config / trial.restore_path updated in place."""

    EXPLOIT = "EXPLOIT"  # internal decision: controller restarts the trial

    def __init__(self, time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: dict | None = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: int | None = None):
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self.rng = random.Random(seed)
        self._last_perturb: Dict[str, float] = {}
        # trial_id -> (score, checkpoint_path, config) at last report
        self._state: Dict[str, tuple] = {}

    def on_trial_result(self, trial: Trial, result: dict) -> str:
        score = self._score(result)
        self._state[trial.trial_id] = (score, trial.checkpoint_path, dict(trial.config))
        t = result.get(self.time_attr, trial.iteration)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval:
            return CONTINUE

        ranked = sorted(self._state.items(), key=lambda kv: kv[1][0])
        n = len(ranked)
        if n < 2:
            # no peer has reported yet (e.g. its actor is still spawning):
            # leave the boundary armed instead of consuming it, so the
            # comparison happens as soon as a peer shows up
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        k = max(1, int(n * self.quantile))
        bottom = [tid for tid, _ in ranked[:k]]
        top = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id not in bottom or trial.trial_id in top:
            return CONTINUE
        donor_id = self.rng.choice(top)
        donor_score, donor_ckpt, donor_cfg = self._state[donor_id]
        if donor_ckpt is None:
            return CONTINUE
        trial.config = self._explore(donor_cfg)
        trial.restore_path = donor_ckpt
        return self.EXPLOIT

    def _explore(self, config: dict) -> dict:
        new = dict(config)
        for key, spec in self.mutations.items():
            if key not in new:
                continue
            if callable(spec):
                new[key] = spec()
            elif isinstance(spec, list):
                if self.rng.random() < self.resample_prob or new[key] not in spec:
                    new[key] = self.rng.choice(spec)
                else:
                    i = spec.index(new[key])
                    i = min(len(spec) - 1, max(0, i + self.rng.choice([-1, 1])))
                    new[key] = spec[i]
            elif isinstance(spec, dict) and "lower" in spec:
                lo, hi = spec["lower"], spec["upper"]
                if self.rng.random() < self.resample_prob:
                    new[key] = self.rng.uniform(lo, hi)
                else:
                    new[key] = min(hi, max(lo, new[key] * self.rng.choice([0.8, 1.2])))
        return new


# Public alias matching the reference's preferred name (reference:
# tune/schedulers/__init__.py exports ASHAScheduler = AsyncHyperBandScheduler)
ASHAScheduler = AsyncHyperBandScheduler
