"""Per-trial tune session: report() / get_checkpoint() inside a trainable.

Equivalent of the reference's tune session (reference: python/ray/tune —
ray.tune.report / ray.train.get_checkpoint inside function trainables).
Reports are buffered in the trial actor and drained by the TuneController;
checkpoints passed to report() are persisted into the trial dir so they
outlive the actor (needed for PBT exploit and resume).
"""
from __future__ import annotations

import os
import shutil
from typing import Any, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import ReportBuffer


class _TuneSession(ReportBuffer):
    def __init__(self, trial_id: str, trial_dir: str, restore_path: str | None,
                 start_iteration: int = 0):
        super().__init__()
        self.trial_id = trial_id
        self.trial_dir = trial_dir
        self.restore_path = restore_path
        self._iteration = start_iteration

    def report(self, metrics: dict, checkpoint: Checkpoint | None = None) -> None:
        with self._lock:
            self._iteration += 1
            entry = {"metrics": dict(metrics), "iteration": self._iteration}
        if checkpoint is not None:
            dest = os.path.join(self.trial_dir, f"checkpoint_{self._iteration:06d}")
            if os.path.abspath(checkpoint.path) != dest:
                shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
            entry["checkpoint_path"] = dest
        self.append(entry)

    def get_checkpoint(self) -> Optional[Checkpoint]:
        if self.restore_path and os.path.isdir(self.restore_path):
            return Checkpoint(self.restore_path)
        return None


_session: _TuneSession | None = None


def init_session(s: _TuneSession) -> None:
    global _session
    _session = s


def get_session() -> _TuneSession:
    if _session is None:
        raise RuntimeError("No tune session — are you inside a trainable?")
    return _session


def report(metrics: dict, *, checkpoint: Checkpoint | None = None) -> None:
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().get_checkpoint()
