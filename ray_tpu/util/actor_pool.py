"""ActorPool — load-balanced work distribution over a fixed actor set.

Equivalent of the reference's ray.util.ActorPool
(reference: python/ray/util/actor_pool.py — submit/map/map_unordered with
get_next/get_next_unordered).
"""
from __future__ import annotations

from typing import Any, Callable, Iterable

import ray_tpu


class ActorPool:
    def __init__(self, actors: Iterable):
        self._idle = list(actors)
        if not self._idle:
            raise ValueError("ActorPool needs at least one actor")
        self._future_to_actor: dict[bytes, Any] = {}
        self._pending: list = []  # (fn, value) waiting for a free actor
        self._index_to_future: dict[int, Any] = {}
        self._next_task_index = 0
        self._next_return_index = 0

    def submit(self, fn: Callable, value: Any) -> None:
        """fn(actor, value) -> ObjectRef (e.g. lambda a, v: a.f.remote(v))."""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref.object_id.binary()] = (actor, ref)
            self._index_to_future[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending.append((fn, value))

    def has_next(self) -> bool:
        return self._next_return_index < self._next_task_index or bool(self._pending)

    def _return_actor(self, ref) -> None:
        actor, _ = self._future_to_actor.pop(ref.object_id.binary())
        if self._pending:
            fn, value = self._pending.pop(0)
            self._idle.append(actor)
            self.submit(fn, value)
        else:
            self._idle.append(actor)

    def get_next(self, timeout: float | None = None) -> Any:
        """Next result in SUBMISSION order."""
        if self._next_return_index >= self._next_task_index:
            raise StopIteration("no pending results")
        # peek — only consume the slot once the result actually resolved,
        # so a timeout is retryable and never skips/leaks a result
        ref = self._index_to_future[self._next_return_index]
        try:
            out = ray_tpu.get(ref, timeout=timeout)
        except ray_tpu.exceptions.GetTimeoutError:
            raise
        except Exception:
            # the task FINISHED (with an error): the actor is free again
            del self._index_to_future[self._next_return_index]
            self._next_return_index += 1
            self._return_actor(ref)
            raise
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        self._return_actor(ref)
        return out

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        """Whichever pending result lands first."""
        refs = [r for _, r in self._future_to_actor.values()]
        if not refs:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        # drop it from the ordered map too
        for idx, f in list(self._index_to_future.items()):
            if f.object_id == ref.object_id:
                del self._index_to_future[idx]
                if idx == self._next_return_index:
                    self._next_return_index += 1
                break
        # the task FINISHED (ready): free the actor BEFORE the get, which
        # re-raises task errors — otherwise a failed task leaks the actor and
        # map_unordered re-selects the same ready-failed ref forever
        self._return_actor(ref)
        return ray_tpu.get(ref)

    def map(self, fn: Callable, values: Iterable) -> Iterable:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable) -> Iterable:
        for v in values:
            self.submit(fn, v)
        while self._future_to_actor or self._pending:
            yield self.get_next_unordered()
