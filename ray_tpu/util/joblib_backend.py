"""joblib parallel backend on the ray_tpu task core.

Equivalent of the reference's joblib integration (reference:
python/ray/util/joblib/__init__.py register_ray() +
ray_backend.py RayBackend) — lets scikit-learn-style code run its
`joblib.Parallel` batches as distributed tasks:

    import joblib
    from ray_tpu.util.joblib_backend import register_ray_tpu
    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu"):
        Parallel(n_jobs=4)(delayed(f)(i) for i in range(100))

Each joblib batch (a picklable BatchedCalls callable) becomes one task;
results are retrieved through a future-like wrapper so joblib's retrieval
machinery (timeouts, callbacks) works unchanged.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Optional

import ray_tpu


class _RefResult:
    """AsyncResult-shaped wrapper over an ObjectRef; the callback (joblib's
    batch-completion accounting) fires from a waiter thread."""

    def __init__(self, ref, callback: Optional[Callable]):
        self._ref = ref
        if callback is not None:
            def waiter():
                try:
                    out = ray_tpu.get(ref)
                except Exception:  # noqa: BLE001 — joblib re-raises via get()
                    return
                callback(out)

            t = threading.Thread(target=waiter, daemon=True)
            t.start()

    def get(self, timeout: Optional[float] = None) -> Any:
        return ray_tpu.get(self._ref, timeout=timeout)


@ray_tpu.remote
def _run_batch(batch: Any) -> Any:
    return batch()


class RayTpuBackend:
    """joblib ParallelBackendBase implementation (duck-typed subclass built
    lazily so importing this module never hard-requires joblib)."""


def _make_backend_class():
    from joblib._parallel_backends import ParallelBackendBase

    class _Backend(ParallelBackendBase):
        supports_timeout = True
        supports_retrieve_callback = True
        uses_threads = False
        supports_sharedmem = False

        def configure(self, n_jobs=1, parallel=None, **backend_kwargs):
            self.parallel = parallel
            self._n_jobs = self.effective_n_jobs(n_jobs)
            return self._n_jobs

        def effective_n_jobs(self, n_jobs):
            if n_jobs == 0:
                raise ValueError("n_jobs == 0 has no meaning")
            if n_jobs and n_jobs > 0:
                return n_jobs
            try:
                return max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
            except Exception:  # noqa: BLE001 — not initialized yet
                return 1

        def apply_async(self, func, callback=None):
            return _RefResult(_run_batch.remote(func), callback)

        # joblib >= 1.3 retrieval path
        def submit(self, func, callback=None):
            return self.apply_async(func, callback)

        def retrieve_result_callback(self, out):
            return out

        def retrieve_result(self, out, timeout=None):
            return out.get(timeout=timeout)

        def abort_everything(self, ensure_ready=True):
            if ensure_ready:
                self.configure(n_jobs=self._n_jobs, parallel=self.parallel)

    return _Backend


_registered = False


def register_ray_tpu() -> None:
    """Register the 'ray_tpu' joblib backend (idempotent)."""
    global _registered
    if _registered:
        return
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray_tpu", _make_backend_class())
    _registered = True
