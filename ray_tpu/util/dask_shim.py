"""Dask-on-ray_tpu: execute dask task graphs on the distributed core.

Equivalent of the reference's dask scheduler shim (reference:
python/ray/util/dask/scheduler.py — ray_dask_get walks the dask graph,
submits one ray task per graph node with upstream ObjectRefs as
arguments, so the object store deduplicates shared intermediates and the
cluster scheduler handles the DAG's parallelism).

The dask GRAPH PROTOCOL is a plain dict — {key: computation} where a
computation is a task tuple ``(callable, *args)``, a key reference, or a
literal — so this shim needs no dask import to work: pass it to
``dask.compute(..., scheduler=ray_dask_get)`` when dask is installed, or
feed it protocol-shaped dicts directly (how the tests drive it).
"""
from __future__ import annotations

from typing import Any, Hashable, Mapping, Sequence

import ray_tpu


@ray_tpu.remote
def _exec_node(func, *args):
    # upstream ObjectRefs in `args` arrive RESOLVED (task-arg semantics)
    return func(*args)


def _is_task(x: Any) -> bool:
    return isinstance(x, tuple) and len(x) > 0 and callable(x[0])


def ray_dask_get(dsk: Mapping[Hashable, Any], keys, **kwargs):
    """Dask custom-scheduler entry point: materialize `keys` from graph
    `dsk`, one ray_tpu task per graph node, dependencies passed as object
    refs. Returns values in the same (possibly nested) structure dask
    uses for `keys`."""
    refs: dict[Hashable, Any] = {}

    def submit(key: Hashable):
        if key in refs:
            return refs[key]
        comp = dsk[key]
        refs[key] = _build(comp)
        return refs[key]

    def _build(comp: Any):
        """computation -> ObjectRef or literal."""
        if _is_task(comp):
            func, *args = comp
            arg_refs = [_resolve_arg(a) for a in args]
            return _exec_node.remote(func, *arg_refs)
        return _resolve_arg(comp)

    def _is_key(a: Any) -> bool:
        # dask keys are strings or tuples like ("sum-<hash>", 0) — the
        # TUPLE ITSELF is the key (literal tuples in dask graphs are
        # expressed as (tuple, [items]), i.e. a task)
        try:
            return a in dsk
        except TypeError:
            return False

    def _resolve_arg(a: Any):
        if _is_task(a):
            # nested task (dask inlines small expressions)
            return _build(a)
        if isinstance(a, (str, bytes, int, float, tuple)) and _is_key(a):
            return submit(a)
        if isinstance(a, list):
            built = [_resolve_arg(x) for x in a]
            if any(_has_ref(b) for b in built):
                return _exec_node.remote(lambda *xs: list(xs), *built)
            return built
        return a

    def _has_ref(x: Any) -> bool:
        return isinstance(x, ray_tpu.ObjectRef)

    def fetch(key_or_nested):
        # dask's get(dsk, keys) convention: LISTS are structure to recurse
        # into; tuples (and everything else) are keys
        if isinstance(key_or_nested, list):
            return [fetch(k) for k in key_or_nested]
        out = submit(key_or_nested)
        return ray_tpu.get(out, timeout=600) if _has_ref(out) else out

    return fetch(keys)


def enable_dask_on_ray_tpu() -> None:
    """Install ray_dask_get as dask's default scheduler (no-op with a
    clear error when dask isn't present — it is not baked into this
    image)."""
    try:
        import dask
    except ImportError as e:
        raise ImportError(
            "dask is not installed in this environment; pass "
            "scheduler=ray_tpu.util.dask_shim.ray_dask_get explicitly "
            "where dask is available") from e
    dask.config.set(scheduler=ray_dask_get)
