"""Distributed tracing — application spans propagated through task calls.

Equivalent of the reference's OpenTelemetry integration (reference:
python/ray/util/tracing/tracing_helper.py — trace context injected into
task metadata at submission, child spans opened around remote execution).
No external SDK: spans ride the existing task-event plane (SPAN events in
the task-event buffer → GCS), and export to the same chrome-trace format
as `state.timeline()`. Semantics follow OTel: a span is a named, timed
block; spans nest via a contextvar; a task submitted inside a span carries
the trace context, and its execution on the worker becomes a child span.

    from ray_tpu.util import tracing

    with tracing.span("ingest", source="s3"):
        refs = [preprocess.remote(x) for x in shards]   # children
        ray_tpu.get(refs)
    tracing.trace_to_chrome(trace_id, "trace.json")
"""
from __future__ import annotations

import contextvars
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Optional

# (trace_id_hex, span_id_hex) of the innermost active span
_current: contextvars.ContextVar[Optional[tuple[str, str]]] = (
    contextvars.ContextVar("ray_tpu_span", default=None)
)

# Process-local span buffer for the fleet trace plane: every recorded
# span is ALSO kept here (bounded ring — oldest drop first) so the serve
# controller can drain it through the same non-blocking metrics poll it
# already runs, without a GCS scan. Deliberately small: a process that is
# never polled (plain driver scripts) just wraps around.
_BUFFER_MAX = 2048
_buffer_lock = threading.Lock()
_buffer: deque = deque(maxlen=_BUFFER_MAX)


def drain_buffered_spans() -> list[dict]:
    """Atomically take (and clear) this process's buffered spans — the
    controller-side trace collector calls this via the piggybacked
    ``metrics_report`` poll. Each entry is a flat span dict:
    {name, kind, trace_id, span_id, parent_span_id, start, end, attrs}."""
    with _buffer_lock:
        out = list(_buffer)
        _buffer.clear()
    return out


def current_context() -> Optional[dict]:
    """Trace context to inject into an outgoing task spec (None when no
    span is active — tracing is opt-in per call tree, so untraced
    workloads pay nothing)."""
    cur = _current.get()
    if cur is None:
        return None
    return {"trace_id": cur[0], "parent_span_id": cur[1]}


@contextmanager
def attach_context(ctx: dict | None):
    """Re-enter a stored trace context on a thread that did not inherit
    the submitter's contextvars — stream pump threads, failover resume
    re-dispatches. Spans opened (and tasks submitted) inside the block
    parent under ``ctx['parent_span_id']``. No-op for ``None``, so
    untraced callers can pass their stored context unconditionally."""
    if not ctx:
        yield
        return
    token = _current.set((ctx["trace_id"], ctx["parent_span_id"]))
    try:
        yield
    finally:
        _current.reset(token)


def _record(name: str, trace_id: str, span_id: str,
            parent_span_id: str | None, start: float, end: float,
            attrs: dict | None, kind: str) -> None:
    from ray_tpu._private.worker import global_worker_or_none

    # buffer first (works even outside a cluster — unit tests and the
    # poll-based fleet collection path don't need the GCS at all)
    with _buffer_lock:
        _buffer.append({
            "name": name, "kind": kind, "trace_id": trace_id,
            "span_id": span_id, "parent_span_id": parent_span_id,
            "start": start, "end": end, "attrs": attrs or {},
        })
    w = global_worker_or_none()
    if w is None or getattr(w, "task_events", None) is None:
        return
    task_id = b"\x00" * 24
    job_id = b"\x00" * 4
    try:
        if w.task_id is not None:
            task_id = w.task_id.binary()
        job_id = w.job_id.binary()
    except Exception:  # noqa: BLE001 — identity is best-effort metadata
        pass
    w.task_events.record(
        task_id=task_id, job_id=job_id, name=name, event="SPAN",
        task_type=kind,
        extra={
            "trace_id": trace_id,
            "span_id": span_id,
            "parent_span_id": parent_span_id,
            "start": start,
            "end": end,
            "attrs": attrs or {},
        },
    )


def record_span(
    name: str,
    *,
    trace_id: str,
    parent_span_id: str | None = None,
    start: float,
    end: float,
    attrs: dict | None = None,
    kind: str = "span",
) -> str:
    """Record a completed span from an explicitly-carried trace context.

    For instrumentation that cannot hold a contextvar open across the
    span's lifetime — e.g. the serve/llm engine, whose request phases run
    on the scheduler thread long after the submitting call returned. The
    caller supplies the stored context and the measured start/end wall
    times; returns the new span id (so phase spans can parent under a
    request span recorded in the same batch)."""
    span_id = os.urandom(8).hex()
    _record(name, trace_id, span_id, parent_span_id, start, end, attrs, kind)
    return span_id


@contextmanager
def span_if_active(name: str, **attrs: Any):
    """Like ``span`` but a no-op when no trace is active: hot paths (the
    serve router, proxies) instrument with this so untraced traffic pays
    one contextvar read and nothing else."""
    if _current.get() is None:
        yield None
        return
    with span(name, **attrs) as ctx:
        yield ctx


@contextmanager
def span(name: str, **attrs: Any):
    """Open a span; nests under the active one; records on exit."""
    parent = _current.get()
    trace_id = parent[0] if parent else os.urandom(8).hex()
    span_id = os.urandom(8).hex()
    token = _current.set((trace_id, span_id))
    start = time.time()
    try:
        yield {"trace_id": trace_id, "span_id": span_id}
    finally:
        _current.reset(token)
        _record(name, trace_id, span_id, parent[1] if parent else None,
                start, time.time(), attrs, kind="span")


@contextmanager
def task_span(spec: dict):
    """Worker-side: wrap task execution as a child span when the submitter
    carried a trace context (no-op otherwise)."""
    ctx = spec.get("trace_ctx")
    if not ctx:
        yield
        return
    span_id = os.urandom(8).hex()
    token = _current.set((ctx["trace_id"], span_id))
    start = time.time()
    try:
        yield
    finally:
        _current.reset(token)
        _record(spec["name"], ctx["trace_id"], span_id,
                ctx.get("parent_span_id"), start, time.time(),
                {"task_id": spec["task_id"].hex()}, kind="task")


def get_trace(trace_id: str, limit: int | None = None) -> list[dict]:
    """All recorded spans of one trace (driver-side, via the GCS).

    The trace-id filter (and the optional ``limit`` cap on returned
    spans) is applied SERVER-side in the GCS — one trace's cost no
    longer scales with total task-event volume."""
    from ray_tpu.util.state import _task_events

    return [
        e for e in _task_events(trace_id=trace_id, limit=limit)
        if e.get("event") == "SPAN" and e.get("trace_id") == trace_id
    ]


def spans_to_chrome(spans: list[dict]) -> list[dict]:
    """Render a list of span dicts (GCS task events OR the flat buffered
    shape the fleet TraceStore holds) as chrome://tracing events."""
    events = []
    for e in sorted(spans, key=lambda e: e["start"]):
        events.append({
            "name": e["name"],
            # the span kind rides the event's task_type slot — the buffer
            # stores it under "type"; accept either key so replayed/legacy
            # events still categorize (regression: tests/test_tracing.py
            # asserts cat == "task" for task-execution spans)
            "cat": (e.get("type") or e.get("task_type")
                    or e.get("kind") or "span"),
            "ph": "X",
            "ts": e["start"] * 1e6,
            "dur": (e["end"] - e["start"]) * 1e6,
            "pid": e.get("node_id", e.get("source", ""))[:8],
            "tid": e.get("worker_id", "")[:8],
            "args": {
                "span_id": e["span_id"],
                "parent_span_id": e.get("parent_span_id"),
                **(e.get("attrs") or {}),
            },
        })
    return events


def trace_to_chrome(trace_id: str, filename: str | None = None,
                    limit: int | None = None):
    """Export one trace as chrome://tracing events (the same consumer as
    state.timeline())."""
    import json

    events = spans_to_chrome(get_trace(trace_id, limit=limit))
    if filename is None:
        return events
    with open(filename, "w") as f:
        json.dump(events, f)
    return None
