"""Application metrics API: Counter / Gauge / Histogram.

Equivalent of the reference's ray.util.metrics
(reference: python/ray/util/metrics.py Counter/Gauge/Histogram over the C++
OpenCensus pipeline src/ray/stats/metric.h:103-160 exported to Prometheus).
Here metrics register into prometheus_client (in-process registry); expose
them with `start_metrics_server(port)` and scrape, or read programmatically
via `collect()`.

Fleet plane (docs/OBSERVABILITY.md "Fleet metrics & goodput"): each process
stays the owner of its own registry; ``collect_families()`` snapshots it
WITH metric kinds preserved, and ``FleetAggregator`` (driven by the Serve
controller) merges many such snapshots into one scrapeable plane — entity
labels per source, per-kind rollups (sum counters, last-write gauges,
bucket-wise histogram merge), and a bounded ring-buffer time-series history
that outlives the processes it sampled.
"""
from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Sequence

logger = logging.getLogger("ray_tpu.metrics")

try:
    import prometheus_client as _prom
    from prometheus_client import CollectorRegistry

    _AVAILABLE = True
except ImportError:  # pragma: no cover - baked into this image
    _AVAILABLE = False

_registry = None
_registry_lock = threading.Lock()


def _get_registry():
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = CollectorRegistry()
        return _registry


class _Metric:
    def __init__(self, name: str, description: str, tag_keys: Sequence[str]):
        if not _AVAILABLE:
            raise RuntimeError("prometheus_client not available")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: dict[str, str] = {}

    def set_default_tags(self, tags: dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _labels(self, tags: dict[str, str] | None):
        merged = {**self._default_tags, **(tags or {})}
        missing = set(self.tag_keys) - set(merged)
        if missing:
            raise ValueError(f"metric {self.name} missing tags: {sorted(missing)}")
        return [merged[k] for k in self.tag_keys]


class Counter(_Metric):
    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._c = _prom.Counter(
            name, description, labelnames=self.tag_keys, registry=_get_registry()
        )

    def inc(self, value: float = 1.0, tags: dict | None = None):
        c = self._c.labels(*self._labels(tags)) if self.tag_keys else self._c
        c.inc(value)


class Gauge(_Metric):
    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._g = _prom.Gauge(
            name, description, labelnames=self.tag_keys, registry=_get_registry()
        )

    def set(self, value: float, tags: dict | None = None):
        g = self._g.labels(*self._labels(tags)) if self.tag_keys else self._g
        g.set(value)


class Histogram(_Metric):
    def __init__(self, name, description="", boundaries=(), tag_keys=()):
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(boundaries)
        kwargs = {"registry": _get_registry(), "labelnames": self.tag_keys}
        if boundaries:
            kwargs["buckets"] = self.boundaries
        self._h = _prom.Histogram(name, description, **kwargs)

    def observe(self, value: float, tags: dict | None = None):
        h = self._h.labels(*self._labels(tags)) if self.tag_keys else self._h
        h.observe(value)


# Idempotent named-metric factories: prometheus_client raises on duplicate
# registration, but library-internal metrics (e.g. the serve/llm engine,
# which may be constructed several times in one process) want one shared
# instrument per name. Keyed on name; kind mismatches fail loudly.
_named: dict[str, _Metric] = {}
_named_lock = threading.Lock()
# names already warned about description drift — warn ONCE per name, not
# once per get (engine construction re-gets every metric)
_desc_warned: set[str] = set()


def _get_named(cls, name: str, description: str, tag_keys, **kwargs):
    with _named_lock:
        m = _named.get(name)
        if m is None:
            m = cls(name, description, tag_keys=tag_keys, **kwargs)
            _named[name] = m
        elif type(m) is not cls:
            raise TypeError(
                f"metric {name!r} already registered as {type(m).__name__}, "
                f"requested {cls.__name__}"
            )
        else:
            # same fail-loudly contract as the kind check: handing back an
            # instrument whose schema differs from what the caller asked
            # for would silently mislabel (tag_keys) or misbucket
            # (boundaries) every later observation
            if tuple(tag_keys) != m.tag_keys:
                raise ValueError(
                    f"metric {name!r} already registered with tag_keys="
                    f"{m.tag_keys}, requested {tuple(tag_keys)}"
                )
            if isinstance(m, Histogram):
                want = tuple(kwargs.get("boundaries") or ())
                if want != m.boundaries:
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"boundaries={m.boundaries}, requested {want}"
                    )
            # description drift is not schema-breaking (the first HELP
            # string keeps being exported) but it means code and docs
            # disagree about what the metric measures — warn once.
            # Omitted descriptions (lookup-style ``counter(name)``) are
            # not drift.
            if (
                description
                and m.description
                and description != m.description
                and name not in _desc_warned
            ):
                _desc_warned.add(name)
                logger.warning(
                    "metric %r re-registered with a different description "
                    "(%r vs original %r); keeping the original — update "
                    "the caller or the docs",
                    name, description, m.description,
                )
        return m


def counter(name: str, description: str = "", tag_keys=()) -> Counter:
    """Get-or-create a process-wide Counter by name."""
    return _get_named(Counter, name, description, tag_keys)


def gauge(name: str, description: str = "", tag_keys=()) -> Gauge:
    """Get-or-create a process-wide Gauge by name."""
    return _get_named(Gauge, name, description, tag_keys)


def histogram(
    name: str, description: str = "", boundaries=(), tag_keys=()
) -> Histogram:
    """Get-or-create a process-wide Histogram by name."""
    return _get_named(
        Histogram, name, description, tag_keys, boundaries=boundaries
    )


def start_metrics_server(port: int = 9090, addr: str = "0.0.0.0"):
    """Expose the registry on http://addr:port/metrics (Prometheus scrape
    target — the analog of the reference's per-node metrics agent).

    Returns ``(server, port)``: the bound WSGI server (call
    ``server.shutdown()`` to stop it) and the ACTUAL bound port, so
    ``port=0`` binds an ephemeral port — multi-process nodes and tests
    can scrape without port collisions."""
    from wsgiref.simple_server import WSGIRequestHandler, make_server

    try:  # threaded scrape handling when the installed client has it
        from prometheus_client.exposition import ThreadingWSGIServer as _Srv
    except ImportError:  # pragma: no cover - baked into this image
        from wsgiref.simple_server import WSGIServer as _Srv

    class _SilentHandler(WSGIRequestHandler):
        def log_message(self, format, *args):
            """Scrapes land every few seconds — keep them off stderr."""

    server = make_server(
        addr, int(port), _prom.make_wsgi_app(registry=_get_registry()),
        server_class=_Srv, handler_class=_SilentHandler,
    )
    thread = threading.Thread(
        target=server.serve_forever, daemon=True, name="metrics-server"
    )
    thread.start()
    return server, server.server_port


def collect(prefix: str | None = None) -> dict[str, float]:
    """Programmatic snapshot: {'name{label=v}': value} for tests/inspection.

    ``prefix`` filters by sample-name prefix (e.g. ``"llm_prefix"``) so
    benchmarks and dashboards can pull one subsystem's metrics without
    walking the whole registry."""
    out = {}
    for family in _get_registry().collect():
        for sample in family.samples:
            if prefix is not None and not sample.name.startswith(prefix):
                continue
            labels = ",".join(f"{k}={v}" for k, v in sorted(sample.labels.items()))
            key = f"{sample.name}{{{labels}}}" if labels else sample.name
            out[key] = sample.value
    return out


# ---------------------------------------------------------------------------
# Fleet metrics plane (docs/OBSERVABILITY.md "Fleet metrics & goodput")
# ---------------------------------------------------------------------------


def collect_families(prefix: str | None = None) -> dict[str, dict]:
    """Structured registry snapshot preserving metric KIND — the fleet
    merge needs per-kind semantics (sum counters, last-write gauges,
    bucket-wise histogram merge) that the flat ``collect()`` mapping
    cannot express.

    -> ``{family_name: {"type", "help", "samples": [{"name", "labels",
    "value"}, ...]}}``. Sample names keep the Prometheus suffix contracts
    (``_total`` for counters; ``_bucket``/``_sum``/``_count`` for
    histograms, with the bucket bound as a ``le`` label); ``_created``
    bookkeeping samples are dropped (timestamps, not mergeable). The
    result is plain JSON-safe dicts, so it crosses actor RPCs as-is —
    this is the payload ``metrics_report()`` control methods return."""
    out: dict[str, dict] = {}
    for family in _get_registry().collect():
        if prefix is not None and not family.name.startswith(prefix):
            continue
        samples = [
            {
                "name": s.name,
                "labels": dict(s.labels),
                "value": float(s.value),
            }
            for s in family.samples
            if not s.name.endswith("_created")
        ]
        out[family.name] = {
            "type": family.type,
            "help": family.documentation,
            "samples": samples,
        }
    return out


def sample_key(name: str, labels: dict[str, str]) -> str:
    """Canonical series key, same format as ``collect()`` keys:
    ``name{k=v,...}`` with labels sorted — history rings and tests agree
    on one spelling."""
    pairs = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{pairs}}}" if pairs else name


def _escape_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", r"\\")
        .replace("\n", r"\n")
        .replace('"', r"\"")
    )


def _format_value(value: float) -> str:
    f = float(value)
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    if f.is_integer():
        return str(int(f))
    return repr(f)


def render_prometheus(families: dict[str, dict]) -> str:
    """Prometheus text exposition (format 0.0.4) of a
    ``collect_families()``-shaped dict — the body served at the
    dashboard's ``/metrics/fleet`` scrape target."""
    lines: list[str] = []
    for fname in sorted(families):
        fam = families[fname]
        help_text = str(fam.get("help") or "").replace("\\", r"\\").replace(
            "\n", r"\n"
        )
        if help_text:
            lines.append(f"# HELP {fname} {help_text}")
        lines.append(f"# TYPE {fname} {fam.get('type') or 'untyped'}")
        for s in fam["samples"]:
            labels = ",".join(
                f'{k}="{_escape_label(v)}"'
                for k, v in sorted(s["labels"].items())
            )
            body = f"{s['name']}{{{labels}}}" if labels else s["name"]
            lines.append(f"{body} {_format_value(s['value'])}")
    return "\n".join(lines) + "\n"


class FleetAggregator:
    """Merges per-process ``collect_families()`` snapshots into one fleet
    plane (driven by the Serve controller, one ``ingest`` per polled
    replica/proxy report).

    - Every source's samples are RELABELED with its entity labels
      (``deployment``/``replica_id``/``pool_role``/...), so per-replica
      series stay distinct at the single scrape target.
    - Rollup series drop ``replica_id`` and merge across sources with
      per-kind semantics: counters and histogram ``_bucket``/``_sum``/
      ``_count`` samples SUM (bucket counts are preserved exactly);
      gauges (and untyped families) are LAST-WRITE in report-stamp order.
    - Each relabeled series also feeds a bounded ring-buffer history
      (``history_samples`` newest ``(stamp, value)`` points, stamped with
      the ingest stamp — the controller's ``obs.clock``). Sources are
      never forgotten: a killed replica's last snapshot keeps the fleet
      counters monotonic and its rings stay queryable post-mortem.
    """

    ROLLUP_DROP = ("replica_id",)

    def __init__(self, history_samples: int = 360):
        self.history_samples = max(1, int(history_samples))
        self._lock = threading.Lock()
        # source key -> {"stamp", "labels", "families"}; insertion order
        # is irrelevant — fleet merges sort by stamp
        self._sources: dict[str, dict] = {}
        # relabeled series key -> deque[(stamp, value)]
        self._history: dict[str, deque] = {}

    def ingest(
        self,
        source: str,
        families: dict[str, dict],
        labels: dict[str, str],
        stamp: float,
    ) -> None:
        """Replace ``source``'s snapshot and append every sample to its
        history ring. Empty label values are dropped (Prometheus treats
        absent and empty labels identically)."""
        labels = {str(k): str(v) for k, v in (labels or {}).items() if v}
        with self._lock:
            self._sources[str(source)] = {
                "stamp": float(stamp),
                "labels": labels,
                "families": families,
            }
            for fam in families.values():
                for s in fam["samples"]:
                    key = sample_key(s["name"], {**s["labels"], **labels})
                    ring = self._history.get(key)
                    if ring is None:
                        ring = deque(maxlen=self.history_samples)
                        self._history[key] = ring
                    ring.append((float(stamp), float(s["value"])))

    def sources(self) -> dict[str, dict]:
        """{source: {"stamp", "labels"}} — who has reported, and when."""
        with self._lock:
            return {
                src: {"stamp": rec["stamp"], "labels": dict(rec["labels"])}
                for src, rec in self._sources.items()
            }

    def fleet_families(self) -> dict[str, dict]:
        """One ``collect_families()``-shaped dict for the whole fleet:
        per-source relabeled samples first, then the rollup samples
        (``replica_id`` dropped, per-kind merge)."""
        with self._lock:
            recs = sorted(
                self._sources.values(), key=lambda rec: rec["stamp"]
            )
            recs = [
                {
                    "stamp": rec["stamp"],
                    "labels": dict(rec["labels"]),
                    "families": rec["families"],
                }
                for rec in recs
            ]
        fams: dict[str, dict] = {}
        # (family, sample name, rollup label items) -> merged value
        rollup: dict[tuple, float] = {}
        for rec in recs:  # stamp order => "last write" = newest report
            for fname, fam in rec["families"].items():
                out = fams.setdefault(
                    fname,
                    {
                        "type": fam.get("type") or "untyped",
                        "help": fam.get("help") or "",
                        "samples": [],
                    },
                )
                summed = out["type"] in ("counter", "histogram")
                for s in fam["samples"]:
                    labels = {**s["labels"], **rec["labels"]}
                    out["samples"].append(
                        {
                            "name": s["name"],
                            "labels": labels,
                            "value": float(s["value"]),
                        }
                    )
                    if not any(k in labels for k in self.ROLLUP_DROP):
                        # nothing to drop: the per-source series IS the
                        # rollup; emitting both would duplicate it
                        continue
                    rl = tuple(sorted(
                        (k, v) for k, v in labels.items()
                        if k not in self.ROLLUP_DROP
                    ))
                    key = (fname, s["name"], rl)
                    if summed:
                        rollup[key] = rollup.get(key, 0.0) + float(s["value"])
                    else:
                        rollup[key] = float(s["value"])
        for (fname, sname, rl) in sorted(rollup, key=str):
            fams[fname]["samples"].append(
                {"name": sname, "labels": dict(rl), "value": rollup[(fname, sname, rl)]}
            )
        return fams

    def fleet_text(self) -> str:
        return render_prometheus(self.fleet_families())

    def history(
        self, series: str | None = None, prefix: str | None = None
    ) -> dict[str, list[tuple[float, float]]]:
        """Ring-buffer time series: ``{series_key: [(stamp, value), ...]}``
        (oldest first). ``series`` selects one exact key (``sample_key``
        spelling); ``prefix`` filters by key prefix; neither returns
        everything. Killed sources' rings remain until process exit."""
        with self._lock:
            if series is not None:
                ring = self._history.get(series)
                return {series: list(ring)} if ring is not None else {}
            return {
                key: list(ring)
                for key, ring in self._history.items()
                if prefix is None or key.startswith(prefix)
            }
