"""Application metrics API: Counter / Gauge / Histogram.

Equivalent of the reference's ray.util.metrics
(reference: python/ray/util/metrics.py Counter/Gauge/Histogram over the C++
OpenCensus pipeline src/ray/stats/metric.h:103-160 exported to Prometheus).
Here metrics register into prometheus_client (in-process registry); expose
them with `start_metrics_server(port)` and scrape, or read programmatically
via `collect()`.
"""
from __future__ import annotations

import threading
from typing import Sequence

try:
    import prometheus_client as _prom
    from prometheus_client import CollectorRegistry

    _AVAILABLE = True
except ImportError:  # pragma: no cover - baked into this image
    _AVAILABLE = False

_registry = None
_registry_lock = threading.Lock()


def _get_registry():
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = CollectorRegistry()
        return _registry


class _Metric:
    def __init__(self, name: str, description: str, tag_keys: Sequence[str]):
        if not _AVAILABLE:
            raise RuntimeError("prometheus_client not available")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: dict[str, str] = {}

    def set_default_tags(self, tags: dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _labels(self, tags: dict[str, str] | None):
        merged = {**self._default_tags, **(tags or {})}
        missing = set(self.tag_keys) - set(merged)
        if missing:
            raise ValueError(f"metric {self.name} missing tags: {sorted(missing)}")
        return [merged[k] for k in self.tag_keys]


class Counter(_Metric):
    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._c = _prom.Counter(
            name, description, labelnames=self.tag_keys, registry=_get_registry()
        )

    def inc(self, value: float = 1.0, tags: dict | None = None):
        c = self._c.labels(*self._labels(tags)) if self.tag_keys else self._c
        c.inc(value)


class Gauge(_Metric):
    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._g = _prom.Gauge(
            name, description, labelnames=self.tag_keys, registry=_get_registry()
        )

    def set(self, value: float, tags: dict | None = None):
        g = self._g.labels(*self._labels(tags)) if self.tag_keys else self._g
        g.set(value)


class Histogram(_Metric):
    def __init__(self, name, description="", boundaries=(), tag_keys=()):
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(boundaries)
        kwargs = {"registry": _get_registry(), "labelnames": self.tag_keys}
        if boundaries:
            kwargs["buckets"] = self.boundaries
        self._h = _prom.Histogram(name, description, **kwargs)

    def observe(self, value: float, tags: dict | None = None):
        h = self._h.labels(*self._labels(tags)) if self.tag_keys else self._h
        h.observe(value)


# Idempotent named-metric factories: prometheus_client raises on duplicate
# registration, but library-internal metrics (e.g. the serve/llm engine,
# which may be constructed several times in one process) want one shared
# instrument per name. Keyed on name; kind mismatches fail loudly.
_named: dict[str, _Metric] = {}
_named_lock = threading.Lock()


def _get_named(cls, name: str, description: str, tag_keys, **kwargs):
    with _named_lock:
        m = _named.get(name)
        if m is None:
            m = cls(name, description, tag_keys=tag_keys, **kwargs)
            _named[name] = m
        elif type(m) is not cls:
            raise TypeError(
                f"metric {name!r} already registered as {type(m).__name__}, "
                f"requested {cls.__name__}"
            )
        else:
            # same fail-loudly contract as the kind check: handing back an
            # instrument whose schema differs from what the caller asked
            # for would silently mislabel (tag_keys) or misbucket
            # (boundaries) every later observation
            if tuple(tag_keys) != m.tag_keys:
                raise ValueError(
                    f"metric {name!r} already registered with tag_keys="
                    f"{m.tag_keys}, requested {tuple(tag_keys)}"
                )
            if isinstance(m, Histogram):
                want = tuple(kwargs.get("boundaries") or ())
                if want != m.boundaries:
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"boundaries={m.boundaries}, requested {want}"
                    )
        return m


def counter(name: str, description: str = "", tag_keys=()) -> Counter:
    """Get-or-create a process-wide Counter by name."""
    return _get_named(Counter, name, description, tag_keys)


def gauge(name: str, description: str = "", tag_keys=()) -> Gauge:
    """Get-or-create a process-wide Gauge by name."""
    return _get_named(Gauge, name, description, tag_keys)


def histogram(
    name: str, description: str = "", boundaries=(), tag_keys=()
) -> Histogram:
    """Get-or-create a process-wide Histogram by name."""
    return _get_named(
        Histogram, name, description, tag_keys, boundaries=boundaries
    )


def start_metrics_server(port: int = 9090) -> None:
    """Expose the registry on http://0.0.0.0:port/metrics (Prometheus
    scrape target — the analog of the reference's per-node metrics agent)."""
    _prom.start_http_server(port, registry=_get_registry())


def collect(prefix: str | None = None) -> dict[str, float]:
    """Programmatic snapshot: {'name{label=v}': value} for tests/inspection.

    ``prefix`` filters by sample-name prefix (e.g. ``"llm_prefix"``) so
    benchmarks and dashboards can pull one subsystem's metrics without
    walking the whole registry."""
    out = {}
    for family in _get_registry().collect():
        for sample in family.samples:
            if prefix is not None and not sample.name.startswith(prefix):
                continue
            labels = ",".join(f"{k}={v}" for k, v in sorted(sample.labels.items()))
            key = f"{sample.name}{{{labels}}}" if labels else sample.name
            out[key] = sample.value
    return out
