"""ray:// client — drive a cluster from OUTSIDE it.

Equivalent of the reference's Ray Client (reference:
python/ray/util/client/ — `ray.init("ray://host:10001")` proxies the core
API over gRPC to a server-side proxy that owns real core workers,
python/ray/util/client/server/proxier.py:49). Same architecture here:

  * ClientServer runs on the head next to the GCS; each client connection
    gets its own server-side CoreWorker (its own job), which OWNS every
    object/actor the client creates — ownership, ref-counting, and lineage
    stay inside the cluster, exactly like the reference's proxied workers.
  * ClientWorker implements the CoreWorker surface the API layer uses
    (put/get/wait/submit_task/submit_actor_task/gcs.call/...) by
    forwarding over one msgpack RPC connection, so `@remote` functions,
    actors, and the state API work unchanged from an out-of-cluster
    process: ray_tpu.init(address="ray://host:port").

Values cross the wire as this framework's own serialization blobs
(cloudpickle + oob buffers), produced/consumed at each end.
"""
from __future__ import annotations

import threading
from typing import Any, Sequence

from ray_tpu._private import serialization as ser
from ray_tpu._private import task_spec as ts
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.rpc import RpcClient, RpcServer

DEFAULT_CLIENT_PORT = 10001


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------


class _ClientSession:
    """One connected client = one dedicated server-side CoreWorker."""

    def __init__(self, node_handle):
        import os as _os

        from ray_tpu._private.worker import CoreWorker

        gcs = node_handle.raylet.gcs
        job_id = JobID(gcs.call("next_job_id")["job_id"])
        self.session_id = _os.urandom(8)
        self.owner = None  # the conn currently speaking for this session
        self.closed = False
        self.worker = CoreWorker(
            mode="driver",
            gcs_address=node_handle.gcs_address,
            raylet_address=node_handle.raylet.address,
            store_socket=node_handle.store_socket,
            job_id=job_id,
            node_id=node_handle.node_id,
        )

    def close(self):
        try:
            self.worker.shutdown()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass


# a dropped connection keeps its session (and everything the session's
# worker owns) alive this long for a reconnect-and-reclaim (reference:
# proxier.py keeps SpecificServers alive briefly across reconnects)
def _reconnect_grace_s() -> float:
    import os as _os

    return float(_os.environ.get("RAY_TPU_CLIENT_RECONNECT_GRACE_S", "30"))


class ClientService:
    """RPC service: client_* methods proxied onto per-connection workers
    (reference: proxier.py routes each client to its SpecificServer)."""

    def __init__(self, node_handle):
        self._node = node_handle
        self._lock = threading.Lock()
        # every live session by id; session.owner is the conn currently
        # speaking for it (None while parked in the grace window)
        self._sessions: dict[bytes, _ClientSession] = {}
        # session_id -> reap timer for parked sessions
        self._reap_timers: dict[bytes, threading.Timer] = {}

    def _session(self, conn) -> _ClientSession:
        s = conn.meta.get("client_session")
        if s is None:
            s = _ClientSession(self._node)
            with self._lock:
                self._sessions[s.session_id] = s
            self._attach(conn, s)
        return s

    def _attach(self, conn, s: _ClientSession) -> None:
        conn.meta["client_session"] = s
        s.owner = conn
        conn.on_close.append(lambda c: self._on_conn_close(c, s))

    def _on_conn_close(self, conn, s: _ClientSession) -> None:
        with self._lock:
            if s.owner is not conn:
                return  # session was stolen by a reconnect, or closed
            s.owner = None
        if getattr(s, "closed", False):
            return
        self._park(s)

    def _park(self, s: _ClientSession) -> None:
        """Connection lost: keep the session for the grace window instead
        of tearing it down — an abrupt disconnect used to free every
        object the client still referenced."""
        grace = _reconnect_grace_s()
        if grace <= 0:
            self._close_session(s)
            return
        timer = threading.Timer(grace, self._reap, args=(s.session_id,))
        timer.daemon = True
        with self._lock:
            self._reap_timers[s.session_id] = timer
        timer.start()

    def _reap(self, session_id: bytes) -> None:
        with self._lock:
            self._reap_timers.pop(session_id, None)
            s = self._sessions.get(session_id)
            if s is None or s.owner is not None:
                return  # reclaimed in the meantime
            del self._sessions[session_id]
        s.close()

    def _close_session(self, s: _ClientSession) -> None:
        with self._lock:
            s.closed = True
            self._sessions.pop(s.session_id, None)
            timer = self._reap_timers.pop(s.session_id, None)
        if timer is not None:
            timer.cancel()
        s.close()

    # -- core API --

    def rpc_client_init(self, conn, msgid, p):
        sid = p.get("session_id") if isinstance(p, dict) else None
        if sid:
            with self._lock:
                session = self._sessions.get(sid)
                if session is not None:
                    timer = self._reap_timers.pop(sid, None)
                    prev_owner = session.owner
                    session.owner = conn
            if session is not None:
                if timer is not None:
                    timer.cancel()
                # steal from a zombie conn the server hasn't seen die yet
                # (client-side drop, NAT timeout) — its eventual close is
                # a no-op because it no longer owns the session. A re-init
                # on the session's CURRENT conn is an idempotent reclaim.
                if prev_owner is not None and prev_owner is not conn:
                    prev_owner.meta.pop("client_session", None)
                conn.meta["client_session"] = session
                conn.on_close.append(
                    lambda c: self._on_conn_close(c, session))
                return {"job_id": session.worker.job_id.binary(),
                        "session_id": session.session_id,
                        "reclaimed": True}
            # grace expired / unknown: do NOT silently mint a session —
            # the client must see session-loss explicitly and re-init
            return {"session_id": b"", "reclaimed": False,
                    "session_lost": True}
        s = self._session(conn)
        return {"job_id": s.worker.job_id.binary(),
                "session_id": s.session_id,
                "reclaimed": False}

    def rpc_client_disconnect(self, conn, msgid, p):
        """Graceful goodbye: close the session NOW instead of parking it
        for the grace window (repeated short-lived clients must not
        accumulate 30s-lived CoreWorkers server-side)."""
        s = conn.meta.get("client_session")
        if s is not None:
            self._close_session(s)
        return {"ok": True}

    def rpc_client_put(self, conn, msgid, p):
        s = self._session(conn)
        value = ser.loads(p["blob"])
        ref = s.worker.put(value)
        return {"oid": ref.binary()}

    def rpc_client_get(self, conn, msgid, p):
        s = self._session(conn)
        refs = [ObjectRef(ObjectID(o)) for o in p["oids"]]
        out = []
        for r in refs:
            try:
                value = s.worker.get(r, timeout=p.get("timeout"))
                out.append({"blob": ser.dumps(value)})
            except Exception as e:  # noqa: BLE001 — ships to the client
                out.append({"error": ser.dumps(e)})
        return {"results": out}

    def rpc_client_wait(self, conn, msgid, p):
        """DEFERRED: wait() parks for up to the client's timeout; it must
        hold its own thread, not one of the RPC pool's — parked waits
        would otherwise starve every other client's calls."""
        import traceback as _tb

        from ray_tpu._private.rpc import RESPONSE, RpcServer

        s = self._session(conn)
        refs = [ObjectRef(ObjectID(o)) for o in p["oids"]]

        def run():
            try:
                ready, not_ready = s.worker.wait(
                    refs, num_returns=p["num_returns"],
                    timeout=p.get("timeout"),
                )
                conn.send([RESPONSE, msgid, True, {
                    "ready": [r.binary() for r in ready],
                    "not_ready": [r.binary() for r in not_ready],
                }])
            except Exception:  # noqa: BLE001 — surface to the client
                conn.send([RESPONSE, msgid, False, _tb.format_exc()])

        threading.Thread(target=run, daemon=True,
                         name="client-wait").start()
        return RpcServer.DEFERRED

    def rpc_client_submit(self, conn, msgid, p):
        s = self._session(conn)
        refs = s.worker.submit_task(p["spec"])
        return {"oids": [r.binary() for r in refs]}

    def rpc_client_submit_actor(self, conn, msgid, p):
        s = self._session(conn)
        refs = s.worker.submit_actor_task(p["spec"], p.get("raylet_address"))
        return {"oids": [r.binary() for r in refs]}

    def rpc_client_actor_addr(self, conn, msgid, p):
        s = self._session(conn)
        addr = s.worker.actor_raylet_address(
            ActorID(p["actor_id"]), timeout=p.get("timeout", 60)
        )
        return {"address": addr}

    def rpc_client_seqno(self, conn, msgid, p):
        s = self._session(conn)
        return {"seqno": s.worker.next_actor_seqno(ActorID(p["actor_id"]))}

    def rpc_client_invalidate_actor(self, conn, msgid, p):
        s = self._session(conn)
        s.worker.invalidate_actor_cache(ActorID(p["actor_id"]))
        return {"ok": True}

    def rpc_client_free(self, conn, msgid, p):
        s = self._session(conn)
        for o in p["oids"]:
            try:
                s.worker.remove_local_ref(o)
            except Exception:  # noqa: BLE001
                pass
        return {"ok": True}

    # -- control-plane passthrough --

    def rpc_client_gcs(self, conn, msgid, p):
        s = self._session(conn)
        return {"result": s.worker.gcs.call(p["method"], p.get("payload"))}

    def rpc_client_peer(self, conn, msgid, p):
        s = self._session(conn)
        target = p["address"]
        if target == s.worker.raylet.address:
            client = s.worker.raylet
        else:
            client = s.worker._peer(target)
        return {"result": client.call(p["method"], p.get("payload"))}


class ClientServer:
    """Listens for ray:// clients (reference: `ray start --head` opens the
    client server on port 10001)."""

    def __init__(self, node_handle, host: str = "0.0.0.0",
                 port: int = DEFAULT_CLIENT_PORT):
        self._server = RpcServer(ClientService(node_handle), host, port)
        self.address = self._server.address

    def stop(self):
        self._server.stop()


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------


class _ReconnectingRpc:
    """Client-side connection with session reclaim: a dropped TCP
    connection heals in place (RpcClient.reconnect) and re-presents the
    session token, so the server re-attaches the SAME proxied CoreWorker
    — every outstanding ObjectRef stays valid. If the reconnect grace
    expired server-side, calls fail with an explicit session-lost error
    instead of silently running against a fresh empty session. Retried
    calls are at-least-once; duplicate task submission is safe because
    task/object ids are client-minted and the store keeps first-writer."""

    def __init__(self, address: str):
        self._rpc = RpcClient(address)
        self._heal_lock = threading.Lock()
        self.session_id: bytes | None = None
        self._session_lost = False

    def init_session(self) -> dict:
        r = self._rpc.call("client_init", {"session_id": self.session_id})
        self.session_id = r["session_id"]
        return r

    def _heal(self) -> None:
        import time

        with self._heal_lock:
            if self._session_lost:
                raise ConnectionError(self._LOST_MSG)
            # a failed send marks the connection dead slightly AFTER the
            # failure surfaces (the reader thread notices the close); spin
            # briefly so reconnect() actually replaces the socket instead
            # of reporting the dying connection as healthy
            deadline = time.monotonic() + 10.0
            while True:
                try:
                    if self._rpc.reconnect():
                        r = self._rpc.call(
                            "client_init", {"session_id": self.session_id})
                        break
                except ConnectionError:
                    pass
                if time.monotonic() > deadline:
                    raise ConnectionError("client server unreachable")
                time.sleep(0.1)
            if not r.get("reclaimed"):
                # STICKY: every later call must keep failing loudly — a
                # silent fresh session would strand the app's old refs
                self._session_lost = True
                raise ConnectionError(self._LOST_MSG)
            self.session_id = r["session_id"]

    _LOST_MSG = ("client session lost (reconnect grace expired on the "
                 "server); call ray_tpu.shutdown() + init() for a fresh "
                 "session — previous ObjectRefs are gone")

    def call(self, method: str, payload: Any = None, timeout=None):
        if self._session_lost:
            raise ConnectionError(self._LOST_MSG)
        try:
            return self._rpc.call(method, payload, timeout=timeout)
        except ConnectionError:
            self._heal()
            return self._rpc.call(method, payload, timeout=timeout)

    def call_async(self, method: str, payload: Any = None):
        """Fire-and-forget sends share call()'s session guarantees: refuse
        after a lost session, and heal-then-retry once on a dead socket so
        async users don't silently bypass the reclaim path."""
        if self._session_lost:
            raise ConnectionError(self._LOST_MSG)
        try:
            return self._rpc.call_async(method, payload)
        except ConnectionError:
            self._heal()
            return self._rpc.call_async(method, payload)

    def close(self) -> None:
        try:
            # graceful goodbye: the server closes the session eagerly
            # instead of parking it for the reconnect grace window
            self._rpc.call("client_disconnect", {}, timeout=5)
        except Exception:  # noqa: BLE001 — already-dead connection is fine
            pass
        self._rpc.close()


class _GcsProxy:
    def __init__(self, rpc):
        self._rpc = rpc

    def call(self, method: str, payload: Any = None, timeout=None):
        return self._rpc.call(
            "client_gcs", {"method": method, "payload": payload},
            timeout=timeout,
        )["result"]

    def call_async(self, method: str, payload: Any = None):
        return self._rpc.call_async(
            "client_gcs", {"method": method, "payload": payload})

    def close(self):
        pass  # the ClientWorker owns the underlying connection


class _PeerProxy:
    def __init__(self, rpc, address: str):
        self._rpc = rpc
        self.address = address

    def call(self, method: str, payload: Any = None, timeout=None):
        return self._rpc.call(
            "client_peer",
            {"address": self.address, "method": method, "payload": payload},
            timeout=timeout,
        )["result"]


class ClientWorker:
    """CoreWorker-surface shim speaking to a ClientServer. Installed via
    set_global_worker, so the whole public API routes through it."""

    mode = "client"

    def __init__(self, address: str):
        self._rpc = _ReconnectingRpc(address)
        self.job_id = JobID(self._rpc.init_session()["job_id"])
        self.gcs = _GcsProxy(self._rpc)
        # server-side raylet address, for kill()'s peer routing
        self.raylet = _PeerProxy(self._rpc, "")
        self._seq_lock = threading.Lock()

    # -- identity helpers the API layer uses --

    def new_task_id(self) -> TaskID:
        return TaskID.for_task(self.job_id)

    def next_actor_seqno(self, actor_id: ActorID) -> int:
        return self._rpc.call(
            "client_seqno", {"actor_id": actor_id.binary()})["seqno"]

    def actor_raylet_address(self, actor_id: ActorID, timeout: float = 60):
        return self._rpc.call(
            "client_actor_addr",
            {"actor_id": actor_id.binary(), "timeout": timeout},
            timeout=timeout + 10,
        )["address"]

    def invalidate_actor_cache(self, actor_id: ActorID) -> None:
        self._rpc.call("client_invalidate_actor",
                       {"actor_id": actor_id.binary()})

    def _peer(self, address: str) -> _PeerProxy:
        return _PeerProxy(self._rpc, address)

    # -- ref counting: releases forwarded to the owning server worker --

    def add_local_ref(self, oid: bytes) -> None:
        pass  # the server-side worker owns the ref bookkeeping

    def remove_local_ref(self, oid: bytes) -> None:
        try:
            self._rpc.call_async("client_free", {"oids": [oid]})
        except Exception:  # noqa: BLE001 — interpreter may be tearing down
            pass

    # -- data plane --

    def put(self, value: Any) -> ObjectRef:
        r = self._rpc.call("client_put", {"blob": ser.dumps(value)})
        return ObjectRef(ObjectID(r["oid"]))

    def get(self, refs, timeout: float | None = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        r = self._rpc.call(
            "client_get",
            {"oids": [x.binary() for x in ref_list], "timeout": timeout},
            timeout=None if timeout is None else timeout + 30,
        )
        values = []
        for item in r["results"]:
            if "error" in item:
                raise ser.loads(item["error"])
            values.append(ser.loads(item["blob"]))
        return values[0] if single else values

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: float | None = None):
        r = self._rpc.call(
            "client_wait",
            {
                "oids": [x.binary() for x in refs],
                "num_returns": num_returns,
                "timeout": timeout,
            },
            timeout=None if timeout is None else timeout + 30,
        )
        by_id = {x.binary(): x for x in refs}
        return ([by_id[o] for o in r["ready"]],
                [by_id[o] for o in r["not_ready"]])

    def as_future(self, ref: ObjectRef):
        from concurrent.futures import Future

        fut: Future = Future()

        def run():
            try:
                fut.set_result(self.get(ref))
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True).start()
        return fut

    # -- task plane --

    def submit_task(self, spec: dict):
        spec = _wire_safe_spec(spec)
        r = self._rpc.call("client_submit", {"spec": spec})
        return [ObjectRef(ObjectID(o)) for o in r["oids"]]

    def submit_actor_task(self, spec: dict, raylet_address: str | None):
        spec = _wire_safe_spec(spec)
        r = self._rpc.call(
            "client_submit_actor",
            {"spec": spec, "raylet_address": raylet_address},
        )
        return [ObjectRef(ObjectID(o)) for o in r["oids"]]

    def shutdown(self) -> None:
        try:
            self._rpc.close()
        except Exception:  # noqa: BLE001
            pass


def _wire_safe_spec(spec: dict) -> dict:
    """Task specs are already msgpack-able dicts of bytes/str/num — assert
    rather than silently shipping something exotic."""
    return dict(spec)


def connect_client(address: str) -> None:
    """ray_tpu.init(address="ray://host:port") entry point."""
    from ray_tpu._private.worker import set_global_worker

    if address.startswith("ray://"):
        address = address[len("ray://"):]
    set_global_worker(ClientWorker(address))
