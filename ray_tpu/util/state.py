"""State API: cluster introspection (list/summarize) + chrome timeline.

Equivalent of the reference's state API and timeline
(reference: python/ray/experimental/state/api.py list_actors/tasks/nodes +
`ray summary`; served by StateAPIManager dashboard/state_aggregator.py:141
over GcsTaskManager task events gcs_task_manager.h:326; chrome trace
ray.timeline python/ray/_private/state.py:435-451).
"""
from __future__ import annotations

import json
from collections import defaultdict
from typing import Any

from ray_tpu._private.worker import global_worker


def list_nodes() -> list[dict]:
    w = global_worker()
    return w.gcs.call("get_nodes")["nodes"]


def list_actors() -> list[dict]:
    w = global_worker()
    return w.gcs.call("list_actors")["actors"]


def cluster_resources() -> dict[str, float]:
    w = global_worker()
    return w.gcs.call("cluster_resources")["total"]


def available_resources() -> dict[str, float]:
    w = global_worker()
    return w.gcs.call("cluster_resources")["available"]


def _task_events(job_id: str | None = None, *,
                 trace_id: str | None = None,
                 limit: int | None = None) -> list[dict]:
    w = global_worker()
    w.task_events.flush()
    req: dict = {"job_id": job_id}
    if trace_id is not None:
        req["trace_id"] = trace_id
    if limit is not None:
        req["limit"] = int(limit)
    return w.gcs.call("list_task_events", req)["events"]


def list_tasks(job_id: str | None = None) -> list[dict]:
    """One row per task with its latest state + timings."""
    rows: dict[str, dict] = {}
    for e in _task_events(job_id):
        row = rows.setdefault(
            e["task_id"],
            {
                "task_id": e["task_id"],
                "name": e["name"],
                "type": e["type"],
                "job_id": e["job_id"],
                "state": "UNKNOWN",
                "node_id": None,
                "worker_id": None,
                "submitted_at": None,
                "started_at": None,
                "finished_at": None,
            },
        )
        ev = e["event"]
        if ev == "SUBMITTED":
            row["submitted_at"] = e["ts"]
            if row["state"] == "UNKNOWN":
                row["state"] = "PENDING"
        elif ev == "RUNNING":
            row["started_at"] = e["ts"]
            row["state"] = "RUNNING"
            row["node_id"] = e["node_id"]
            row["worker_id"] = e["worker_id"]
        elif ev in ("FINISHED", "FAILED"):
            row["finished_at"] = e["ts"]
            row["state"] = ev
            row["node_id"] = e["node_id"]
            row["worker_id"] = e["worker_id"]
    return list(rows.values())


def summarize_tasks(job_id: str | None = None) -> dict:
    """`ray summary tasks` equivalent: per-name state counts + wall time."""
    summary: dict[str, Any] = defaultdict(
        lambda: {"states": defaultdict(int), "total_time_s": 0.0, "count": 0}
    )
    for t in list_tasks(job_id):
        s = summary[t["name"]]
        s["states"][t["state"]] += 1
        s["count"] += 1
        if t["started_at"] and t["finished_at"]:
            s["total_time_s"] += t["finished_at"] - t["started_at"]
    return {
        name: {**v, "states": dict(v["states"])} for name, v in summary.items()
    }


def timeline(filename: str | None = None) -> list[dict] | None:
    """Chrome-trace events (chrome://tracing 'X' phases): one row per
    worker, one slice per task execution."""
    events = []
    for t in list_tasks():
        if not (t["started_at"] and t["finished_at"]):
            continue
        events.append(
            {
                "name": t["name"],
                "cat": t["type"],
                "ph": "X",
                "ts": t["started_at"] * 1e6,
                "dur": (t["finished_at"] - t["started_at"]) * 1e6,
                "pid": t["node_id"] or "node",
                "tid": t["worker_id"] or "worker",
                "args": {"task_id": t["task_id"], "state": t["state"]},
            }
        )
    if filename is None:
        return events
    with open(filename, "w") as f:
        json.dump(events, f)
    return None


def summary() -> dict:
    """Cluster-level rollup (`ray status`-shaped)."""
    nodes = list_nodes()
    return {
        "nodes": {
            "total": len(nodes),
            "alive": sum(1 for n in nodes if n["alive"]),
        },
        "resources": {
            "total": cluster_resources(),
            "available": available_resources(),
        },
        "actors": {
            "total": len(list_actors()),
        },
    }


def event_stats() -> dict[str, dict]:
    """Per-process control-loop latency stats (reference: the event_stats
    section of `ray debug_state.txt`, src/ray/common/asio/
    instrumented_io_context.h). Process-local: covers this driver's RPC
    servers and raylet loops when they run in-process."""
    from ray_tpu._private import event_stats as es

    return es.snapshot()


def debug_state() -> str:
    """Human-readable debug dump (the reference's debug_state.txt)."""
    from ray_tpu._private import event_stats as es

    lines = ["== event_stats ==", es.summary_string()]
    try:
        nodes = list_nodes()
        lines.append("== nodes ==")
        for n in nodes:
            nid = n["node_id"]
            nid = nid.hex() if isinstance(nid, bytes) else str(nid)
            lines.append(
                f"{nid[:12]} alive={n.get('alive')} "
                f"disk={n.get('disk_used_frac', float('nan')):.2f} "
                f"load={n.get('load', 0)}"
            )
    except Exception:  # noqa: BLE001 — dump what we can without a cluster
        pass
    return "\n".join(lines)
