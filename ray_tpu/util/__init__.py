from ray_tpu.util.placement_group import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
    slice_bundle,
)
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

__all__ = [
    "PlacementGroup",
    "placement_group",
    "remove_placement_group",
    "slice_bundle",
    "NodeAffinitySchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
]
