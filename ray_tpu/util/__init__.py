from ray_tpu.util.placement_group import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
    slice_bundle,
)
from ray_tpu.util.dask_shim import ray_dask_get
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

__all__ = [
    "PlacementGroup",
    "placement_group",
    "remove_placement_group",
    "slice_bundle",
    "NodeAffinitySchedulingStrategy",
    "ray_dask_get",
    "PlacementGroupSchedulingStrategy",
]
