"""multiprocessing.Pool-compatible shim over tasks.

Equivalent of the reference's ray.util.multiprocessing
(reference: python/ray/util/multiprocessing/pool.py — drop-in Pool whose
workers are cluster tasks, so a Pool program scales past one host without
code changes).
"""
from __future__ import annotations

from typing import Any, Callable, Iterable

import ray_tpu


class AsyncResult:
    def __init__(self, refs: list, single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: float | None = None):
        out = ray_tpu.get(self._refs, timeout=timeout)
        return out[0] if self._single else out

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(
            self._refs, num_returns=len(self._refs), timeout=0
        )
        return len(ready) == len(self._refs)

    def wait(self, timeout: float | None = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)


class Pool:
    """Process-pool API; each apply/map item is a cluster task."""

    def __init__(self, processes: int | None = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._processes = processes  # advisory: tasks schedule on CPU slots
        self._closed = False

    def _check(self):
        if self._closed:
            raise ValueError("Pool not running")

    def apply_async(self, func: Callable, args=(), kwds=None) -> AsyncResult:
        self._check()
        remote_fn = ray_tpu.remote(func)
        return AsyncResult([remote_fn.remote(*args, **(kwds or {}))], single=True)

    def apply(self, func: Callable, args=(), kwds=None):
        return self.apply_async(func, args, kwds).get(timeout=None)

    def map_async(self, func: Callable, iterable: Iterable) -> AsyncResult:
        self._check()
        remote_fn = ray_tpu.remote(func)
        return AsyncResult([remote_fn.remote(x) for x in iterable], single=False)

    def map(self, func: Callable, iterable: Iterable) -> list:
        return self.map_async(func, iterable).get(timeout=None)

    def imap(self, func: Callable, iterable: Iterable):
        self._check()
        remote_fn = ray_tpu.remote(func)
        refs = [remote_fn.remote(x) for x in iterable]
        for r in refs:
            yield ray_tpu.get(r, timeout=None)

    def imap_unordered(self, func: Callable, iterable: Iterable):
        self._check()
        remote_fn = ray_tpu.remote(func)
        pending = [remote_fn.remote(x) for x in iterable]
        while pending:
            # wait() may return MORE than num_returns ready refs in one
            # scan pass — yield every one, or they'd be silently dropped
            ready, pending = ray_tpu.wait(pending, num_returns=1, timeout=None)
            for r in ready:
                yield ray_tpu.get(r, timeout=None)

    def starmap(self, func: Callable, iterable: Iterable) -> list:
        self._check()
        remote_fn = ray_tpu.remote(func)
        return ray_tpu.get(
            [remote_fn.remote(*args) for args in iterable], timeout=None
        )

    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
