"""Placement groups: gang reservation of resource bundles.

Equivalent of the reference's placement group API
(reference: python/ray/util/placement_group.py:41 PlacementGroup, :146
placement_group(); GCS-side 2-phase reservation in
gcs_placement_group_scheduler.cc:884). TPU-first addition:
``slice_bundle(n_hosts, chips_per_host)`` builds a STRICT_SPREAD group whose
bundles co-locate on one ICI domain, the unit of gang-scheduled SPMD jobs.
"""
from __future__ import annotations

import time
from typing import Sequence

from ray_tpu._private.ids import PlacementGroupID
from ray_tpu._private.worker import global_worker
from ray_tpu.exceptions import PlacementGroupUnavailableError


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: list[dict], strategy: str):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self._state = "UNKNOWN"

    def ready(self, timeout: float = 30.0) -> bool:
        worker = global_worker()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            r = worker.gcs.call("get_placement_group", {"pg_id": self.id.binary()})
            pg = r["pg"]
            if pg and pg["state"] == "CREATED":
                self._state = "CREATED"
                return True
            time.sleep(0.05)
        return False

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self.ready(timeout=timeout_seconds)

    @property
    def bundle_specs(self) -> list[dict]:
        return self.bundles

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles, self.strategy))


def placement_group(
    bundles: Sequence[dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
) -> PlacementGroup:
    if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        raise ValueError(f"invalid strategy {strategy}")
    worker = global_worker()
    pg_id = PlacementGroupID.of(worker.job_id)
    bundles = [dict(b) for b in bundles]
    worker.gcs.call(
        "create_placement_group",
        {"pg_id": pg_id.binary(), "bundles": bundles, "strategy": strategy},
    )
    return PlacementGroup(pg_id, bundles, strategy)


def slice_bundle(
    n_hosts: int, chips_per_host: int = 4, cpus_per_host: float = 1
) -> PlacementGroup:
    """Reserve an ICI-connected slice: one bundle per host, all within one
    ici-domain (STRICT_SPREAD + domain-affinity in the bundle scheduler)."""
    return placement_group(
        [{"CPU": cpus_per_host, "TPU": float(chips_per_host)} for _ in range(n_hosts)],
        strategy="STRICT_SPREAD",
    )


def remove_placement_group(pg: PlacementGroup) -> None:
    worker = global_worker()
    worker.gcs.call("remove_placement_group", {"pg_id": pg.id.binary()})
