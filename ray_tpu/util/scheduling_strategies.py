"""Scheduling strategies (reference: python/ray/util/scheduling_strategies.py
— PlacementGroupSchedulingStrategy:15, NodeAffinitySchedulingStrategy:41)."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: Any
    placement_group_bundle_index: int = 0
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: bytes
    soft: bool = False
