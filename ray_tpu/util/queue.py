"""Distributed FIFO queue backed by a named actor.

Equivalent of the reference's ray.util.queue.Queue
(reference: python/ray/util/queue.py — actor-backed queue with
put/get/qsize and blocking variants).
"""
from __future__ import annotations

import time
from typing import Any

import ray_tpu
from ray_tpu.actor import ActorClass


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        from collections import deque

        self._maxsize = maxsize
        self._q = deque()

    def put(self, item) -> bool:
        if self._maxsize > 0 and len(self._q) >= self._maxsize:
            return False
        self._q.append(item)
        return True

    def get(self):
        if not self._q:
            return False, None
        return True, self._q.popleft()

    def qsize(self) -> int:
        return len(self._q)


class Queue:
    def __init__(self, maxsize: int = 0, name: str | None = None):
        self._actor = ActorClass(_QueueActor, num_cpus=0.01, name=name).remote(maxsize)

    def put(self, item: Any, block: bool = True, timeout: float | None = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self._actor.put.remote(item), timeout=60):
                return
            if not block:
                raise Full
            if deadline is not None and time.monotonic() > deadline:
                raise Full
            time.sleep(0.01)

    def get(self, block: bool = True, timeout: float | None = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self._actor.get.remote(), timeout=60)
            if ok:
                return item
            if not block:
                raise Empty
            if deadline is not None and time.monotonic() > deadline:
                raise Empty
            time.sleep(0.01)

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote(), timeout=60)

    def empty(self) -> bool:
        return self.qsize() == 0

    def shutdown(self) -> None:
        ray_tpu.kill(self._actor)
