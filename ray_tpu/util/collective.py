"""Collective communication groups for actors/tasks (host-side).

Equivalent of the reference's ray.util.collective
(reference: python/ray/util/collective/collective.py:120-615 —
init_collective_group / allreduce / allgather / reducescatter / broadcast /
barrier / send / recv over NCCL (GPU) or Gloo (CPU) groups).

TPU mapping (SURVEY.md §5.8): the DEVICE data plane does not live here —
in-graph collectives are XLA's (`jax.lax.psum` et al. under pjit/shard_map
over the ICI mesh), and hosts are bootstrapped with
`jax.distributed.initialize`. This module is the HOST-side (Gloo-analog)
backend: numpy collectives among actor/task processes for control-plane
sync, rendezvous, and CPU tensor exchange — coordinated by a named
rendezvous actor, with the shared-memory object store as the data plane.
"""
from __future__ import annotations

import time
from typing import Any

import numpy as np

import ray_tpu
from ray_tpu.actor import ActorClass

_GROUP_ACTOR_PREFIX = "rt_collective:"
_POLL_S = 0.005


class _GroupCoordinator:
    """Named actor holding per-operation contributions. Members push their
    chunk and poll for completion (actor methods are short and non-blocking,
    so the one-at-a-time actor queue never deadlocks)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._ops: dict[tuple, dict[int, Any]] = {}
        self._results: dict[tuple, list] = {}
        self._mailbox: dict[tuple, Any] = {}

    def contribute(self, op_key: tuple, rank: int, value) -> None:
        op_key = tuple(op_key)
        pend = self._ops.setdefault(op_key, {})
        pend[rank] = value
        if len(pend) == self.world_size:
            self._results[op_key] = [pend[r] for r in range(self.world_size)]
            del self._ops[op_key]

    def result(self, op_key: tuple):
        """(ready, values) — values ordered by rank once all arrived."""
        op_key = tuple(op_key)
        vals = self._results.get(op_key)
        return (True, vals) if vals is not None else (False, None)

    def ack(self, op_key: tuple, rank: int) -> None:
        """Garbage-collect a result once every rank has read it."""
        op_key = tuple(op_key)
        acks = self._ops.setdefault(("ack",) + op_key, {})
        acks[rank] = True
        if len(acks) == self.world_size:
            self._results.pop(op_key, None)
            del self._ops[("ack",) + op_key]

    def post(self, key: tuple, value) -> None:
        self._mailbox[tuple(key)] = value

    def take(self, key: tuple):
        return self._mailbox.pop(tuple(key), None)


class CollectiveGroup:
    """One member's view of a collective group."""

    def __init__(self, group_name: str, world_size: int, rank: int, handle):
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self._coord = handle
        self._seq = 0

    def _next_key(self, op: str) -> tuple:
        self._seq += 1
        return (op, self._seq)

    def _exchange(self, op: str, value, timeout: float) -> list:
        """All ranks contribute; returns rank-ordered contributions."""
        key = self._next_key(op)
        ray_tpu.get(
            self._coord.contribute.remote(key, self.rank, value), timeout=timeout
        )
        deadline = time.monotonic() + timeout
        while True:
            ready, vals = ray_tpu.get(
                self._coord.result.remote(key), timeout=timeout
            )
            if ready:
                self._coord.ack.remote(key, self.rank)
                return vals
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"collective {op} timed out in group {self.group_name!r} "
                    f"(rank {self.rank}/{self.world_size})"
                )
            time.sleep(_POLL_S)

    # -- collectives (reference API shape, collective.py:120-615) --

    def barrier(self, timeout: float = 120.0) -> None:
        self._exchange("barrier", None, timeout)

    def allreduce(self, array, op: str = "sum", timeout: float = 120.0):
        vals = self._exchange("allreduce", np.asarray(array), timeout)
        stack = np.stack(vals)
        if op == "sum":
            return stack.sum(axis=0)
        if op == "mean":
            return stack.mean(axis=0)
        if op == "max":
            return stack.max(axis=0)
        if op == "min":
            return stack.min(axis=0)
        raise ValueError(f"unsupported reduce op {op!r}")

    def allgather(self, array, timeout: float = 120.0) -> list:
        return [np.asarray(v) for v in self._exchange("allgather", np.asarray(array), timeout)]

    def broadcast(self, array, src_rank: int = 0, timeout: float = 120.0):
        vals = self._exchange(
            "broadcast", np.asarray(array) if self.rank == src_rank else None, timeout
        )
        return np.asarray(vals[src_rank])

    def reducescatter(self, array, op: str = "sum", timeout: float = 120.0):
        """Reduce then scatter equal chunks: rank r gets chunk r."""
        reduced = self.allreduce(array, op=op, timeout=timeout)
        chunks = np.array_split(reduced, self.world_size)
        return chunks[self.rank]

    def send(self, array, dst_rank: int, tag: int = 0, timeout: float = 120.0) -> None:
        key = ("p2p", self.rank, dst_rank, tag)
        ray_tpu.get(
            self._coord.post.remote(key, np.asarray(array)), timeout=timeout
        )

    def recv(self, src_rank: int, tag: int = 0, timeout: float = 120.0):
        key = ("p2p", src_rank, self.rank, tag)
        deadline = time.monotonic() + timeout
        while True:
            v = ray_tpu.get(self._coord.take.remote(key), timeout=timeout)
            if v is not None:
                return np.asarray(v)
            if time.monotonic() > deadline:
                raise TimeoutError(f"recv from rank {src_rank} timed out")
            time.sleep(_POLL_S)


_groups: dict[str, CollectiveGroup] = {}


def init_collective_group(
    world_size: int, rank: int, group_name: str = "default", timeout: float = 120.0
) -> CollectiveGroup:
    """Join (rank 0 creates) the named group; blocks until all members join
    (reference: collective.py init_collective_group / declare_collective_group).
    """
    actor_name = _GROUP_ACTOR_PREFIX + group_name
    if rank == 0:
        coord = ActorClass(
            _GroupCoordinator, num_cpus=0.01, name=actor_name
        ).remote(world_size)
    else:
        deadline = time.monotonic() + timeout
        while True:
            try:
                coord = ray_tpu.get_actor(actor_name)
                break
            except ValueError:
                if time.monotonic() > deadline:
                    raise TimeoutError(f"group {group_name!r} never created")
                time.sleep(0.05)
    g = CollectiveGroup(group_name, world_size, rank, coord)
    g.barrier(timeout=timeout)
    _groups[group_name] = g
    return g


def get_group(group_name: str = "default") -> CollectiveGroup:
    g = _groups.get(group_name)
    if g is None:
        raise ValueError(f"collective group {group_name!r} not initialized here")
    return g


def destroy_collective_group(group_name: str = "default") -> None:
    """Tear down the group's coordinator actor. Callable from any process
    (the coordinator is a named actor), member or not."""
    _groups.pop(group_name, None)
    try:
        ray_tpu.kill(ray_tpu.get_actor(_GROUP_ACTOR_PREFIX + group_name))
    except Exception:  # noqa: BLE001 — already gone
        pass


# module-level convenience mirroring the reference's functional API
def allreduce(array, op: str = "sum", group_name: str = "default"):
    return get_group(group_name).allreduce(array, op=op)


def allgather(array, group_name: str = "default"):
    return get_group(group_name).allgather(array)


def broadcast(array, src_rank: int = 0, group_name: str = "default"):
    return get_group(group_name).broadcast(array, src_rank=src_rank)


def reducescatter(array, op: str = "sum", group_name: str = "default"):
    return get_group(group_name).reducescatter(array, op=op)


def barrier(group_name: str = "default"):
    get_group(group_name).barrier()


def send(array, dst_rank: int, group_name: str = "default", tag: int = 0):
    get_group(group_name).send(array, dst_rank, tag=tag)


def recv(src_rank: int, group_name: str = "default", tag: int = 0):
    return get_group(group_name).recv(src_rank, tag=tag)
