"""Model multiplexing: many models per replica with LRU residency.

Equivalent of the reference's serve multiplexing
(reference: python/ray/serve/multiplex.py — @serve.multiplexed loader with
max_num_models_per_replica LRU). TPU note: evicting a model frees its HBM
only once all device buffers are dropped, so the loader should return
device arrays owned solely by the cache entry.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable


class _MultiplexedLoader:
    def __init__(self, loader: Callable[[str], Any], max_num_models: int):
        self._loader = loader
        self._max = max_num_models
        self._lock = threading.Lock()
        self._models: "OrderedDict[str, Any]" = OrderedDict()

    def __call__(self, model_id: str) -> Any:
        with self._lock:
            model = self._models.get(model_id)
            if model is not None:
                self._models.move_to_end(model_id)
                return model
        # load OUTSIDE the lock (loads are slow)
        model = self._loader(model_id)
        to_unload = []
        with self._lock:
            existing = self._models.get(model_id)
            if existing is not None:
                # lost a racing load: keep the cached one, drop our copy so
                # its device buffers (HBM) free promptly
                self._models.move_to_end(model_id)
                to_unload.append(model)
                model = existing
            else:
                self._models[model_id] = model
                while len(self._models) > self._max:
                    _, old = self._models.popitem(last=False)
                    to_unload.append(old)
        for m in to_unload:
            unload = getattr(m, "unload", None)
            if callable(unload):
                unload()
        return model

    @property
    def resident_models(self) -> list[str]:
        with self._lock:
            return list(self._models)


def multiplexed(
    _loader: Callable | None = None, *, max_num_models_per_replica: int = 3
):
    """Wrap a model-loading function with per-replica LRU residency:

        @serve.deployment
        class M:
            def __init__(self):
                self.get_model = serve.multiplexed(
                    load_model, max_num_models_per_replica=3)
            def __call__(self, req):
                return self.get_model(req["model_id"]).predict(req["x"])
    """

    def wrap(loader):
        return _MultiplexedLoader(loader, max_num_models_per_replica)

    return wrap if _loader is None else wrap(_loader)
