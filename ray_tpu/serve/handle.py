"""DeploymentHandle + router: client-side replica scheduling.

Equivalent of the reference's handle/router pair
(reference: python/ray/serve/handle.py:298 DeploymentHandle;
serve/_private/router.py:922 Router, :308 PowerOfTwoChoicesReplicaScheduler,
assign_replica :278). The handle tracks its own in-flight counts per replica
and picks the lower-loaded of two random replicas. Batched methods ship as
ordinary single-payload calls: coalescing happens REPLICA-side (replica.py
_ReplicaBatchQueue, matching the reference's serve/batching.py:337), so
callers from different processes share one padded batch.
"""
from __future__ import annotations

import logging
import os
import random
import threading
import time
import uuid
import zlib
from concurrent.futures import Future
from typing import Any

import ray_tpu
from ray_tpu._private import chaos
from ray_tpu.exceptions import EngineOverloadedError
from ray_tpu.util import metrics, tracing

logger = logging.getLogger("ray_tpu.serve.handle")

_TABLE_REFRESH_S = 0.25
# controller-outage survival: a failed routing-table RPC keeps serving
# the cached table (the controller only ever removes entries the
# failover path already tolerates), bounded by a short per-RPC timeout
# so one refresh can't stall a dispatch for the whole outage
_TABLE_RPC_TIMEOUT_S = 5.0
# the shed flag is only as fresh as the table that carried it: once the
# table is older than this, fail OPEN (engines still shed engine-side)
# instead of rejecting all traffic on a flag the dead controller can no
# longer retract
_SHED_MAX_AGE_S = 3.0
# how long a mid-stream failover RESUME keeps retrying through transient
# EngineOverloadedError (draining-replica race, momentary saturation)
# before failing the half-delivered stream
_RESUME_OVERLOAD_RETRY_S = 10.0
# resume-retry backoff schedule (resume_backoff_s): first retry ~base,
# doubling per attempt up to cap, each jittered into [span/2, span]
_RESUME_BACKOFF_BASE_S = 0.05
_RESUME_BACKOFF_CAP_S = 1.0
# --- prefix-aware routing (fleet-scale KV caching) ---
# compute at most this many leading chain digests per dispatch: deeper
# matches are indistinguishable to the router, and the per-replica
# summary the controller ships is itself bounded
_PREFIX_MATCH_BLOCKS = 16
# load-balance escape hatch: honor the longest prefix match only while
# the target's tracked in-flight load is within this many requests of
# the least-loaded candidate — past that, fall back to power-of-two so
# a hot prefix cannot hotspot one replica
_PREFIX_MAX_SKEW = 4
# "0" disables prefix preference (the bench's private-cache baseline);
# re-read at every table refresh, so flipping it needs no new router
_PREFIX_ROUTING_ENV = "RAY_TPU_PREFIX_ROUTING"


def resume_backoff_s(seed: int, attempt: int, *,
                     base: float = _RESUME_BACKOFF_BASE_S,
                     cap: float = _RESUME_BACKOFF_CAP_S) -> float:
    """Seeded exponential backoff with jitter for the mid-stream RESUME
    retry loop: attempt N sleeps in [span/2, span] where
    span = min(cap, base * 2**N). A replica kill failing dozens of
    streams at once must not re-dispatch them in lockstep — the fixed
    cadence it replaces hammered the survivor with a thundering herd —
    so the jitter spreads resumes out while the per-stream seed keeps
    any one stream's schedule deterministic and testable. The OVERALL
    retry window (_RESUME_OVERLOAD_RETRY_S) is unchanged."""
    span = min(cap, base * (2.0 ** min(attempt, 30)))
    jitter = random.Random((int(seed) << 20) ^ int(attempt)).random()
    return span * (0.5 + 0.5 * jitter)


class DeploymentResponse:
    """Result of handle.method.remote(): resolve with .result()
    (reference: serve/handle.py DeploymentResponse)."""

    def __init__(self, ref=None, future: Future | None = None, on_done=None):
        self._ref = ref
        self._future = future
        self._on_done = on_done
        self._done = False

    def result(self, timeout: float | None = 60.0) -> Any:
        import concurrent.futures

        from ray_tpu.exceptions import GetTimeoutError

        try:
            if self._future is not None:
                out = self._future.result(timeout)
            else:
                out = ray_tpu.get(self._ref, timeout=timeout)
        except (GetTimeoutError, concurrent.futures.TimeoutError):
            # the request is STILL running on the replica — keep the
            # in-flight count until it actually finishes (the router sweep
            # reclaims it then)
            raise
        except BaseException:
            self._mark_done()
            raise
        self._mark_done()
        return out

    def _mark_done(self) -> None:
        if not self._done:
            self._done = True
            if self._on_done:
                self._on_done()

    @property
    def ref(self):
        return self._ref


class DeploymentResponseGenerator:
    """Result of calling a GENERATOR deployment method: iterate to receive
    chunks as the replica produces them (reference: serve/handle.py
    DeploymentResponseGenerator). Values (not refs) are yielded — the
    handle resolves each chunk as it arrives."""

    def __init__(self, ref_gen, on_done=None,
                 chunk_timeout_s: float | None = 120.0):
        self._ref_gen = ref_gen
        self._on_done = on_done
        self._done = False
        # actor id (bytes) of the replica serving this stream; set by the
        # router at dispatch so failover can exclude the dead replica
        self.replica_actor_id: bytes | None = None
        # per-chunk fetch budget; None = wait forever (slow LLM prefill /
        # long tool calls can legitimately exceed any fixed gap). Set via
        # handle.options(stream_chunk_timeout_s=...).
        self._timeout = chunk_timeout_s

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        try:
            ref = next(self._ref_gen)
        except StopIteration:
            self._mark_done()
            raise
        except BaseException:
            self._mark_done()
            raise
        return ray_tpu.get(ref, timeout=self._timeout)

    def _mark_done(self) -> None:
        if not self._done:
            self._done = True
            if self._on_done:
                self._on_done()

    @property
    def completed_ref(self):
        return self._ref_gen.completed_ref


def _failover_cause(e: BaseException) -> BaseException:
    """Unwrap a TaskError to the replica-side exception for retryability
    classification (worker.py re-raises .cause where picklable, but the
    streaming marker path can still surface the wrapper)."""
    from ray_tpu.exceptions import TaskError

    if isinstance(e, TaskError) and e.cause is not None:
        return e.cause
    return e


class ResumableStreamGenerator:
    """A streamed call that survives replica death mid-stream.

    Wraps dispatch-to-one-replica (``dispatch(payload, exclude)``): when
    the serving replica dies (ActorError — including the engine watchdog's
    EngineDiedError — worker crash, lost chunk, dropped connection), it
    builds a resume payload from every chunk already delivered
    (``resume(chunks)``), excludes the dead replica, and re-dispatches to
    a survivor. Chunks must be dicts carrying ``index_key`` with the
    ABSOLUTE chunk index; duplicates from the resumed stream are dropped
    so the caller sees each index exactly once, gap-free.
    """

    def __init__(self, dispatch, payload, resume, *, index_key: str = "index",
                 max_failovers: int = 2):
        self._dispatch = dispatch
        self._payload = payload
        self._resume = resume
        self._index_key = index_key
        self._max_failovers = max_failovers
        self._inner = None
        self.chunks: list = []   # every chunk delivered to the caller
        self.failovers = 0
        self._exclude: set[bytes] = set()
        self._overload_deadline: float | None = None
        self._overload_attempt = 0
        # per-stream backoff seed: request_id when the payload carries one
        # (so a stream's retry schedule is reproducible), else the payload
        # repr — distinct streams land on distinct jitter either way
        rid = (payload.get("request_id")
               if isinstance(payload, dict) else None)
        self._backoff_seed = zlib.crc32(
            str(rid if rid is not None else repr(payload)).encode())
        # chunks are pulled on pump threads that don't inherit the
        # caller's contextvars, so the trace context (if any) is captured
        # HERE — construction happens under the proxy's root span — and
        # failover spans are recorded from it explicitly
        self._trace_ctx = tracing.current_context()

    def __iter__(self):
        return self

    def __next__(self):
        from ray_tpu.exceptions import (
            ActorError,
            ObjectLostError,
            WorkerCrashedError,
        )

        retryable = (ActorError, WorkerCrashedError, ObjectLostError,
                     ConnectionError)
        while True:
            try:
                if self._inner is None:
                    # re-attach the stored trace context: after a failover
                    # this runs on a pump thread with no inherited
                    # contextvars, and the resume dispatch must still
                    # parent the survivor's spans under the original trace
                    with tracing.attach_context(self._trace_ctx):
                        self._inner = self._dispatch(
                            self._payload, frozenset(self._exclude)
                        )
                chunk = next(self._inner)
            except StopIteration:
                raise
            except BaseException as e:  # noqa: BLE001 — classify below
                cause = _failover_cause(e)
                if (isinstance(cause, EngineOverloadedError)
                        and self.failovers > 0):
                    # a resume re-dispatch raced a draining/overloaded
                    # replica. The FIRST dispatch propagates overload (the
                    # caller gets 503 + Retry-After), but once chunks have
                    # been delivered the lossless-failover contract says
                    # this stream must finish — retry briefly instead of
                    # failing a half-delivered stream.
                    now = time.monotonic()
                    if self._overload_deadline is None:
                        self._overload_deadline = (
                            now + _RESUME_OVERLOAD_RETRY_S)
                    if now > self._overload_deadline:
                        raise
                    self._inner = None
                    self._payload = self._resume(list(self.chunks))
                    time.sleep(resume_backoff_s(
                        self._backoff_seed, self._overload_attempt))
                    self._overload_attempt += 1
                    continue
                if (
                    not isinstance(cause, retryable)
                    or self.failovers >= self._max_failovers
                ):
                    raise
                self._overload_deadline = None
                self._overload_attempt = 0
                self.failovers += 1
                aid = getattr(self._inner, "replica_actor_id", None)
                if aid is not None:
                    self._exclude.add(aid)
                if self._trace_ctx is not None:
                    # stitch the failover into the request's trace: the
                    # resume re-dispatch below opens a fresh dispatch span
                    # on the surviving replica, and this marker explains
                    # WHY there are two engine subtrees in one trace
                    tracing.record_span(
                        "handle.resume",
                        trace_id=self._trace_ctx["trace_id"],
                        parent_span_id=self._trace_ctx["parent_span_id"],
                        start=time.time(),
                        end=time.time(),
                        attrs={
                            "failover": self.failovers,
                            "excluded_replica": (aid.hex()[:12]
                                                 if aid else None),
                            "delivered_chunks": len(self.chunks),
                            "cause": type(cause).__name__,
                        },
                    )
                self._payload = self._resume(list(self.chunks))
                self._inner = None
                continue
            idx = chunk.get(self._index_key) if isinstance(chunk, dict) else None
            if idx is None:
                self.chunks.append(chunk)
                return chunk
            if idx < len(self.chunks):
                continue  # duplicate from the resumed stream — drop
            if idx > len(self.chunks):
                raise RuntimeError(
                    f"stream gap: expected chunk {len(self.chunks)}, "
                    f"got {idx}"
                )
            self.chunks.append(chunk)
            return chunk


class _Router:
    """Shared per-process router state: routing table cache + in-flight
    accounting + batchers. One per (app, deployment)."""

    _instances: dict = {}
    _instances_lock = threading.Lock()

    @classmethod
    def get(cls, app_name: str, deployment_name: str) -> "_Router":
        key = (app_name, deployment_name)
        with cls._instances_lock:
            r = cls._instances.get(key)
            if r is None:
                r = cls(app_name, deployment_name)
                cls._instances[key] = r
            return r

    @classmethod
    def reset_all(cls) -> None:
        with cls._instances_lock:
            cls._instances.clear()

    def __init__(self, app_name: str, deployment_name: str):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self.router_id = uuid.uuid4().hex[:12]
        self._lock = threading.Lock()
        self._replicas: list = []
        self._batch_configs: dict[str, dict] = {}
        self._stream_methods: set[str] = set()
        self._max_ongoing = 8
        self._inflight: dict[bytes, int] = {}  # actor_id -> count
        self._outstanding: dict[bytes, bytes] = {}  # object_id -> actor_id
        self._last_refresh = 0.0
        self._controller = None
        # monotonic stamp of the last SUCCESSFUL table fetch (shed aging)
        self._table_at = 0.0
        # controller re-resolution backoff (seeded like the resume path:
        # every router in the process must not hammer the GCS in lockstep
        # when a restarted controller comes back)
        self._ctrl_attempt = 0
        self._next_ctrl_retry = 0.0
        self._ctrl_backoff_seed = zlib.crc32(self.router_id.encode())
        # cluster-wide admission: the controller marks the deployment shed
        # when the whole fleet is saturated (fleet_saturated); data-plane
        # dispatches then fail fast with EngineOverloadedError instead of
        # queuing doomed work (proxies map it to 503 + Retry-After)
        self._shed = False
        # class-aware partial shed: when preemption is exhausted fleet-wide
        # but capacity remains for higher classes, the controller names the
        # priority classes to reject (batch first) instead of flipping the
        # whole-deployment shed bit — docs/SERVING_LLM.md "Priority &
        # preemption"
        self._shed_classes: tuple = ()
        self._m_shed = metrics.counter(
            "llm_requests_shed",
            "Requests shed at admission while the fleet is saturated, "
            "by priority class",
            tag_keys=("app", "deployment", "priority"),
        )
        # Seeded tie-break RNG: routers replay identical choice sequences
        # under the chaos harness (module-level random would interleave
        # with every other consumer in the process).
        self._rng = random.Random(zlib.crc32(self.router_id.encode()))
        # prefix-aware routing state, refreshed with the table:
        # actor id -> frozenset of hex chain digests its caches hold
        self._prefix_summaries: dict[bytes, frozenset] = {}
        self._prefix_block_size: int | None = None
        self._prefix_vocab_size: int | None = None
        self._prefix_routing = (
            os.environ.get(_PREFIX_ROUTING_ENV, "1") != "0"
        )
        self._m_prefix_hits = metrics.counter(
            "llm_router_prefix_hits",
            "Dispatches routed to the replica holding the longest "
            "matching prefix chain",
            tag_keys=("app", "deployment"),
        )

    # -- table management --

    def _controller_handle(self):
        if self._controller is None:
            from ray_tpu.serve.controller import CONTROLLER_NAME

            self._controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return self._controller

    def _invalidate_controller(self) -> None:
        """Forget the cached controller handle after a failed RPC. A
        controller restarted in place (the raylet supervisor path) keeps
        its actor id, but a recreated one does not — re-resolving by
        name on the next attempt covers both, and the seeded backoff
        spreads the re-resolve attempts of every router in the process."""
        with self._lock:
            self._controller = None
            self._next_ctrl_retry = time.monotonic() + resume_backoff_s(
                self._ctrl_backoff_seed, self._ctrl_attempt
            )
            self._ctrl_attempt += 1

    def _refresh(self, force: bool = False) -> None:
        """Refresh the routing table. During a controller outage this
        DEGRADES instead of failing: the cached table keeps serving and
        the controller handle is re-resolved under backoff once the
        supervisor restarts it. 'app/deployment not found' is only
        raised on a SUCCESSFUL fetch that proves the absence."""
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_refresh < _TABLE_REFRESH_S:
                return
            if self._controller is None and now < self._next_ctrl_retry:
                return  # outage backoff: keep serving the cached table
            self._last_refresh = now
            load_report = {
                (self.app_name, self.deployment_name): sum(self._inflight.values())
            }
        self._sweep()
        try:
            table = ray_tpu.get(
                self._controller_handle().get_routing_table.remote(
                    self.router_id,
                    {tuple(k): v for k, v in load_report.items()},
                ),
                timeout=_TABLE_RPC_TIMEOUT_S,
            )
        except Exception as e:  # noqa: BLE001 — controller outage
            self._invalidate_controller()
            logger.warning(
                "routing-table refresh for %s/%s failed (controller "
                "down?); serving cached table: %r",
                self.app_name, self.deployment_name, e,
            )
            return
        app = table["apps"].get(self.app_name)
        if app is None:
            raise RuntimeError(f"serve application {self.app_name!r} not found")
        dep = app["deployments"].get(self.deployment_name)
        if dep is None:
            raise RuntimeError(
                f"deployment {self.deployment_name!r} not found in app "
                f"{self.app_name!r}"
            )
        with self._lock:
            self._replicas = dep["replicas"]
            self._batch_configs = dep["batch_configs"]
            self._stream_methods = set(dep.get("stream_methods", ()))
            self._max_ongoing = dep["max_ongoing_requests"]
            self._shed = bool(dep.get("shed", False))
            self._shed_classes = tuple(dep.get("shed_classes", ()))
            self._prefix_summaries = {
                aid: frozenset(digests)
                for aid, digests in (dep.get("prefix_summaries") or {}).items()
            }
            self._prefix_block_size = dep.get("prefix_block_size")
            self._prefix_vocab_size = dep.get("prefix_vocab_size")
            self._prefix_routing = (
                os.environ.get(_PREFIX_ROUTING_ENV, "1") != "0"
            )
            self._table_at = time.monotonic()
            self._ctrl_attempt = 0
            self._next_ctrl_retry = 0.0

    # -- in-flight accounting --

    def _decrement(self, oid: bytes) -> None:
        """Primary decrement path: DeploymentResponse.result() on_done."""
        with self._lock:
            aid = self._outstanding.pop(oid, None)
            if aid is not None:
                self._inflight[aid] = max(0, self._inflight.get(aid, 1) - 1)

    def _sweep(self) -> None:
        """Safety net for responses whose .result() is never called: drop
        outstanding entries whose result landed (or was evicted). Runs at
        most once per table refresh — NOT per dispatch (a per-dispatch sweep
        would cost O(outstanding) store round-trips per call)."""
        worker = ray_tpu.worker.global_worker()
        from ray_tpu._private.ids import ObjectID

        with self._lock:
            snapshot = list(self._outstanding.items())
        for oid, aid in snapshot:
            # status(): 'present' OR 'evicted' both mean the call finished
            if worker.store.status(ObjectID(oid)) != "missing":
                self._decrement(oid)

    def _pick_replica(self, deadline: float, exclude: frozenset = frozenset(),
                      prefix_digests: tuple | None = None,
                      route_info: dict | None = None):
        """Prefix-aware placement over power-of-two load balancing.
        ``exclude`` holds actor ids (bytes) of replicas the caller knows
        are dead — the failover path skips them until the controller's
        reconcile removes them from the routing table; it COMPOSES with
        the prefix preference (dead replicas are filtered first, then the
        prefix scorer runs over the survivors) rather than bypassing it.
        When ``prefix_digests`` names the prompt's leading chain digests,
        the replica whose advertised caches hold the longest matching
        chain wins — unless its load skew trips the escape hatch
        (_PREFIX_MAX_SKEW), in which case plain power-of-two resumes.
        Tie-breaking samples from the router's seeded RNG so choice
        sequences replay deterministically under the chaos harness.
        ``route_info`` (when given) is filled with the decision the
        dispatch span reports: strategy, candidate count, prefix match
        length, and whether the skew escape hatch fired."""
        info = route_info if route_info is not None else {}
        while True:
            self._refresh()
            with self._lock:
                replicas = [
                    r for r in self._replicas
                    if r._actor_id.binary() not in exclude
                ]
                if replicas:
                    info["candidates"] = len(replicas)
                    if len(replicas) == 1:
                        info["strategy"] = "single"
                        return replicas[0]
                    if prefix_digests:
                        info["prefix_blocks"] = len(prefix_digests)
                        choice = self._prefix_choice_locked(
                            replicas, prefix_digests, info
                        )
                        if choice is not None:
                            self._m_prefix_hits.inc(
                                tags={"app": self.app_name,
                                      "deployment": self.deployment_name}
                            )
                            info["strategy"] = "prefix"
                            return choice
                    a, b = self._rng.sample(replicas, 2)
                    la = self._inflight.get(a._actor_id.binary(), 0)
                    lb = self._inflight.get(b._actor_id.binary(), 0)
                    info["strategy"] = "p2c"
                    return a if la <= lb else b
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no RUNNING replicas for {self.app_name}/"
                    f"{self.deployment_name}"
                )
            time.sleep(0.1)

    def _prefix_choice_locked(self, replicas: list,
                              prefix_digests: tuple,
                              route_info: dict | None = None):
        """Score each candidate by how many LEADING digests of the
        prompt's chain its advertised summary holds; -> the best replica,
        or None to fall back to power-of-two (no replica matches, or the
        winner is too loaded relative to the least-loaded candidate).
        Ties prefer the less-loaded replica, then table order — fully
        deterministic given one routing table."""
        if route_info is None:
            route_info = {}
        best = None
        best_match = 0
        best_load = 0
        min_load: int | None = None
        for r in replicas:
            aid = r._actor_id.binary()
            load = self._inflight.get(aid, 0)
            if min_load is None or load < min_load:
                min_load = load
            resident = self._prefix_summaries.get(aid)
            if not resident:
                continue
            match = 0
            for d in prefix_digests:
                if d not in resident:
                    break
                match += 1
            if match > best_match or (
                match == best_match and match > 0 and load < best_load
            ):
                best, best_match, best_load = r, match, load
        route_info["matched_blocks"] = best_match
        if best is None or best_match == 0:
            return None
        if best_load - (min_load or 0) > _PREFIX_MAX_SKEW:
            route_info["skew_escape"] = True
            return None  # escape hatch: hot prefix must not hotspot
        return best

    def _prompt_digests(self, payload: dict) -> tuple | None:
        """Leading chain digests (hex) of a fresh ``__call__`` prompt,
        computed in the SAME digest space as the replicas' block chains
        (kv_cache._block_key over encode_text-style tokens). Returns
        None whenever the prefix path should not apply: routing disabled,
        no summaries advertised yet, a failover resume (``prior_tokens``
        payloads keep today's dispatch path), or a payload the router
        cannot tokenize."""
        if payload.get("prior_tokens"):
            return None
        with self._lock:
            if not self._prefix_routing:
                return None
            bs = self._prefix_block_size
            vocab = self._prefix_vocab_size
            have_summaries = any(self._prefix_summaries.values())
        if not bs or not have_summaries:
            return None
        prompt = payload.get("prompt")
        try:
            if isinstance(prompt, str):
                if not vocab:
                    return None
                # mirror serve.llm.api.encode_text byte-for-byte
                tokens = [b % vocab for b in prompt.encode("utf-8")]
            else:
                tokens = list(prompt or ())
            if len(tokens) < bs:
                return None
            from ray_tpu.serve.llm.kv_cache import _block_key

            digest = b""
            out = []
            for i in range(min(len(tokens) // bs, _PREFIX_MATCH_BLOCKS)):
                digest = _block_key(digest, tokens[i * bs:(i + 1) * bs])
                out.append(digest.hex())
            return tuple(out) or None
        except Exception as e:  # noqa: BLE001 — unroutable payload shape
            logger.debug(
                "prefix digests skipped for %s/%s: %r",
                self.app_name, self.deployment_name, e,
            )
            return None

    # -- call paths --

    def call(self, method_name: str, args: tuple, kwargs: dict,
             options: dict | None = None,
             exclude: frozenset = frozenset()) -> DeploymentResponse:
        options = options or {}
        chaos.fire("handle.dispatch", method=method_name)
        self._refresh()
        with self._lock:
            bc = self._batch_configs.get(method_name)
        if bc is not None and (len(args) != 1 or kwargs):
            # the @serve.batch contract is one positional payload per call
            # (the method receives the list); extra args/kwargs would be
            # silently dropped replica-side, so reject them here
            raise TypeError(
                f"batched method {self.deployment_name}.{method_name} "
                f"takes exactly one positional argument per call, got "
                f"args={len(args)} kwargs={sorted(kwargs)}"
            )
        with self._lock:
            is_stream = method_name in self._stream_methods
            shed = self._shed
            shed_classes = self._shed_classes
            if ((shed or shed_classes)
                    and time.monotonic() - self._table_at > _SHED_MAX_AGE_S):
                # stale flag during a controller outage: age it out and
                # fail open — the saturated engines still shed for
                # themselves, but an unreachable controller must not keep
                # rejecting traffic it can no longer observe
                shed = self._shed = False
                shed_classes = self._shed_classes = ()
        req_priority = "default"
        if args and isinstance(args[0], dict):
            req_priority = str(args[0].get("priority", "default"))
        shed_this = shed or req_priority in shed_classes
        if shed_this and not exclude and (is_stream or method_name == "__call__"):
            # fleet-wide saturation: reject NEW data-plane work before it
            # queues — either the whole deployment (shed) or just the named
            # priority classes once preemption is exhausted (shed_classes;
            # batch first). Control methods — cancel, stats, debug — still
            # pass; failover resumes carry ``exclude`` and are never shed
            # so a half-delivered stream always finishes.
            self._m_shed.inc(tags={"app": self.app_name,
                                   "deployment": self.deployment_name,
                                   "priority": req_priority})
            detail = ("all replicas saturated (queue backlog + KV pressure "
                      "on every replica)" if shed else
                      f"preemption exhausted fleet-wide; class "
                      f"{req_priority!r} is being shed")
            # traced callers get a shed span (recorded on the exception
            # exit) so the TraceStore's tail sampler retains the trace
            with tracing.span_if_active(
                "handle.shed",
                deployment=f"{self.app_name}/{self.deployment_name}",
                priority=req_priority,
                class_shed=not shed,
            ):
                raise EngineOverloadedError(
                    f"{self.app_name}/{self.deployment_name}: {detail}; "
                    "shedding at admission — retry later"
                )
        # prefix-aware placement applies to fresh generation dispatches
        # only: __call__ with a dict payload and no prior_tokens (resumes
        # and control methods keep the plain path — but still compose
        # with ``exclude`` inside _pick_replica)
        prefix_digests = None
        if method_name == "__call__" and args and isinstance(args[0], dict):
            prefix_digests = self._prompt_digests(args[0])
        route_info: dict = {}
        replica = self._pick_replica(
            time.monotonic() + 30, exclude, prefix_digests, route_info
        )
        aid = replica._actor_id.binary()
        # when the caller carries a trace, open a dispatch span so the
        # replica task (whose trace_ctx is captured at .remote() time)
        # parents under it; no-op for untraced callers. The routing
        # decision rides the span: which replica won, by which strategy,
        # how much of the prompt's prefix it advertised, and whether the
        # load-skew escape hatch overrode a prefix match.
        dispatch_span = tracing.span_if_active(
            "handle.dispatch",
            deployment=f"{self.app_name}/{self.deployment_name}",
            method=method_name,
            replica=aid.hex()[:12],
            strategy=route_info.get("strategy"),
            candidates=route_info.get("candidates", 0),
            matched_blocks=route_info.get("matched_blocks", 0),
            skew_escape=route_info.get("skew_escape", False),
            excluded=len(exclude),
        )
        if is_stream:
            # generator replica method: dispatch through the streaming
            # call path so chunks seal (and are fetchable) as produced
            with dispatch_span:
                gen = replica.rt_call_stream.options(
                    num_returns="streaming"
                ).remote(method_name, args, kwargs)
            oid = gen.completed_ref.object_id.binary()
            with self._lock:
                self._inflight[aid] = self._inflight.get(aid, 0) + 1
                self._outstanding[oid] = aid
            out = DeploymentResponseGenerator(
                gen, on_done=lambda: self._decrement(oid),
                chunk_timeout_s=options.get("stream_chunk_timeout_s", 120.0))
            out.replica_actor_id = aid
            return out
        with dispatch_span:
            ref = replica.rt_call.remote(method_name, args, kwargs)
        oid = ref.object_id.binary()
        with self._lock:
            self._inflight[aid] = self._inflight.get(aid, 0) + 1
            self._outstanding[oid] = aid
        resp = DeploymentResponse(ref=ref, on_done=lambda: self._decrement(oid))
        # same contract as the stream path: callers running their own
        # retry loop (e.g. the prefill-handoff seal) need to know which
        # replica served — or died serving — this call so they can
        # exclude it on the next attempt
        resp.replica_actor_id = aid
        return resp

    def broadcast(self, method_name: str, args: tuple = (),
                  kwargs: dict | None = None, timeout: float = 30.0) -> list:
        """Dispatch a unary method to EVERY running replica and collect the
        results (None where a replica failed). Used for operations that
        must reach whichever replica owns some state — e.g. cancelling a
        stream that power-of-two routing placed on an unknown replica."""
        self._refresh(force=True)
        with self._lock:
            replicas = list(self._replicas)
        refs = []
        for replica in replicas:
            try:
                refs.append(replica.rt_call.remote(
                    method_name, tuple(args), kwargs or {}))
            except Exception:  # noqa: BLE001 — dead replica: skip it
                refs.append(None)
        results = []
        for ref in refs:
            if ref is None:
                results.append(None)
                continue
            try:
                results.append(ray_tpu.get(ref, timeout=timeout))
            except Exception:  # noqa: BLE001 — dead replica: skip it
                results.append(None)
        return results

class _HandleMethod:
    def __init__(self, router: _Router, method_name: str,
                 options: dict | None = None):
        self._router = router
        self._method_name = method_name
        self._options = options

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._router.call(self._method_name, args, kwargs,
                                 options=self._options)


class DeploymentHandle:
    """Callable handle to a deployment; picklable (rebuilds its router from
    the named controller on the other side)."""

    _OPTION_KEYS = frozenset({"stream_chunk_timeout_s"})

    def __init__(self, deployment_name: str, app_name: str = "default",
                 _options: dict | None = None):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._handle_options = _options or {}

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._handle_options))

    def options(self, **kwargs) -> "DeploymentHandle":
        """Per-call options on a derived handle (reference:
        serve/handle.py DeploymentHandle.options). Supported:
        stream_chunk_timeout_s — per-chunk fetch budget for generator
        methods (None waits forever)."""
        unknown = set(kwargs) - self._OPTION_KEYS
        if unknown:
            raise TypeError(f"unknown handle options: {sorted(unknown)}")
        return DeploymentHandle(self.deployment_name, self.app_name,
                                {**self._handle_options, **kwargs})

    @property
    def _router(self) -> _Router:
        return _Router.get(self.app_name, self.deployment_name)

    def __getattr__(self, name: str) -> _HandleMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return _HandleMethod(self._router, name, self._handle_options)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._router.call("__call__", args, kwargs,
                                 options=self._handle_options)

    def stream_methods(self, force: bool = False) -> set:
        """Names of the deployment's generator (streaming) methods
        (cached routing table unless ``force``)."""
        router = self._router
        router._refresh(force=force)
        with router._lock:
            return set(router._stream_methods)

    def broadcast(self, method_name: str, *args, **kwargs) -> list:
        """Call a unary method on EVERY running replica; -> list of results
        (None where a replica failed). For state that lives on an unknown
        replica — e.g. ``handle.broadcast("cancel", request_id)`` reaches
        whichever replica is serving the stream (cancel is idempotent)."""
        return self._router.broadcast(method_name, args, kwargs)

    def stream_with_failover(self, payload: dict, *, resume,
                             method: str = "__call__",
                             index_key: str = "index",
                             max_failovers: int = 2):
        """Stream ``method(payload)`` with mid-stream replica failover:
        on replica death, ``resume(chunks_so_far)`` builds the re-submit
        payload and the call is re-dispatched to a surviving replica,
        deduplicating by ``index_key``. See serve.llm.stream_tokens for
        the LLM resume recipe (prior_tokens + deterministic sampling)."""
        def dispatch(p, exclude):
            return self._router.call(method, (p,), {},
                                     options=self._handle_options,
                                     exclude=exclude)

        return ResumableStreamGenerator(
            dispatch, payload, resume,
            index_key=index_key, max_failovers=max_failovers,
        )
