"""@deployment decorator, Deployment, and Application (bind graph).

Equivalent of the reference's deployment API
(reference: python/ray/serve/api.py:265 @serve.deployment;
serve/deployment.py Deployment.bind; graph build
serve/_private/deployment_graph_build.py). Bind arguments that are
Applications become DeploymentHandles at replica init (model composition).
"""
from __future__ import annotations

from typing import Any, Callable

from ray_tpu._private import task_spec as ts
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig


class Deployment:
    def __init__(self, func_or_class: Callable, name: str, config: DeploymentConfig):
        self.func_or_class = func_or_class
        self.name = name
        self.config = config

    def options(self, **opts) -> "Deployment":
        import dataclasses

        cfg_fields = {f.name for f in dataclasses.fields(DeploymentConfig)}
        cfg_updates = {k: v for k, v in opts.items() if k in cfg_fields}
        cfg = dataclasses.replace(self.config, **cfg_updates)
        if "autoscaling_config" in opts and isinstance(opts["autoscaling_config"], dict):
            cfg.autoscaling_config = AutoscalingConfig(**opts["autoscaling_config"])
        name = opts.get("name", self.name)
        return Deployment(self.func_or_class, name, cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __call__(self, *a, **k):
        raise TypeError(
            f"deployment {self.name} cannot be called directly; deploy it with "
            "serve.run() and call the returned handle"
        )


class Application:
    """A bound deployment (+ its transitively bound children)."""

    def __init__(self, deployment: Deployment, args: tuple, kwargs: dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs

    def flatten(self) -> list["Application"]:
        """Self + all child Applications appearing in bind args."""
        out = [self]
        seen = {id(self)}

        def visit(v):
            if isinstance(v, Application):
                if id(v) not in seen:
                    seen.add(id(v))
                    out.append(v)
                    for a in list(v.args) + list(v.kwargs.values()):
                        visit(a)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    visit(x)
            elif isinstance(v, dict):
                for x in v.values():
                    visit(x)

        for a in list(self.args) + list(self.kwargs.values()):
            visit(a)
        return out

    def build_spec(self, app_name: str) -> dict:
        """Controller-side deployment spec for THIS node of the graph."""
        from ray_tpu.serve.replica import HandleArg

        def swap(v):
            if isinstance(v, Application):
                return HandleArg(v.deployment.name, app_name)
            if isinstance(v, (list, tuple)):
                return type(v)(swap(x) for x in v)
            if isinstance(v, dict):
                return {k: swap(x) for k, x in v.items()}
            return v

        return {
            "name": self.deployment.name,
            "callable_blob": ts.dumps_function(self.deployment.func_or_class),
            "init_args": tuple(swap(a) for a in self.args),
            "init_kwargs": {k: swap(v) for k, v in self.kwargs.items()},
            "config": self.deployment.config,
        }


def deployment(
    _func_or_class: Callable | None = None,
    *,
    name: str | None = None,
    num_replicas: int | None = None,
    max_ongoing_requests: int = 8,
    autoscaling_config: dict | AutoscalingConfig | None = None,
    ray_actor_options: dict | None = None,
    health_check_period_s: float = 1.0,
    user_config: dict | None = None,
):
    """Convert a class or function into a servable Deployment
    (reference: serve/api.py:265)."""

    if isinstance(autoscaling_config, dict):
        autoscaling_config = AutoscalingConfig(**autoscaling_config)

    def wrap(func_or_class):
        cfg = DeploymentConfig(
            num_replicas=num_replicas or 1,
            max_ongoing_requests=max_ongoing_requests,
            autoscaling_config=autoscaling_config,
            ray_actor_options=dict(ray_actor_options or {}),
            health_check_period_s=health_check_period_s,
            user_config=user_config,
        )
        return Deployment(func_or_class, name or func_or_class.__name__, cfg)

    return wrap if _func_or_class is None else wrap(_func_or_class)
