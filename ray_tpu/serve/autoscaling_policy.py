"""Replica autoscaling policy — pure math, unit-testable.

Equivalent of the reference's serve autoscaling policy
(reference: python/ray/serve/_private/autoscaling_policy.py:12
calculate_desired_num_replicas, :78 smoothing/bounds), extended with a
signal-driven policy over `serve.llm` AutoscalingSnapshot dicts: the
controller feeds per-replica engine saturation (queue-wait p95, KV-pool
pressure, deadline-miss / rejection rates) instead of raw HTTP
concurrency, which is what the SLO-aware serving literature asks for —
model saturation, not request counts.

One-clock rule (PR 4): any time read in this module or in the
controller's aggregation path uses obs.clock/obs.wall — snapshot
freshness is judged on the same monotonic clock the engine stamps.
"""
from __future__ import annotations

import math
from typing import Mapping, Sequence

from ray_tpu.serve.config import AutoscalingConfig


def calculate_desired_num_replicas(
    config: AutoscalingConfig,
    total_ongoing_requests: float,
    current_num_replicas: int,
) -> int:
    """Desired replicas from aggregate in-flight load.

    desired = current * (per-replica load / target), smoothed separately for
    up- and down-scaling, clamped to [min, max].
    """
    if current_num_replicas <= 0:
        # scale-from-zero: enough replicas to cover the queue at target load
        raw = total_ongoing_requests / max(config.target_ongoing_requests, 1e-9)
        desired = math.ceil(raw)
    else:
        per_replica = total_ongoing_requests / current_num_replicas
        error_ratio = per_replica / max(config.target_ongoing_requests, 1e-9)
        smoothing = (
            config.upscale_smoothing_factor
            if error_ratio >= 1.0
            else config.downscale_smoothing_factor
        )
        # move a `smoothing` fraction of the way toward the raw target
        raw = current_num_replicas * (1.0 + (error_ratio - 1.0) * smoothing)
        desired = math.ceil(raw) if error_ratio >= 1.0 else math.floor(raw)
    return max(config.min_replicas, min(config.max_replicas, desired))


def snapshot_is_hot(config: AutoscalingConfig, snap: Mapping) -> bool:
    """One replica's engine snapshot trips a scale-up threshold.

    Hot means the engine itself is saturating: requests wait too long at
    admission, the paged KV pool is nearly spent, deadlines are being
    missed, or admission control is already rejecting.

    ``config.signal_mode`` scopes which signals count — disaggregated
    prefill/decode pools scale on DISJOINT signals (ROADMAP item 1), so
    a burst of long cold prompts grows only the prefill pool while KV
    pressure from long generations grows only the decode pool:

      "prefill": admission-side — queue-wait p95 and rejections (TTFT).
      "decode":  generation-side — KV pressure, deadline misses, and
                 (when configured) decode-step p50 (TPOT).
      "all":     every signal (single-pool serving, the default).
    """
    mode = getattr(config, "signal_mode", "all")
    if mode in ("all", "prefill"):
        if (snap.get("queue_wait_p95_s", 0.0)
                >= config.upscale_queue_wait_p95_s):
            return True
        if snap.get("rejection_rate", 0.0) > 0.0:
            return True
    if mode in ("all", "decode"):
        # KV pressure sees both cache tiers: kv_pressure_two_tier
        # discounts device pressure by host-resident (cheaply promotable)
        # blocks, so a replica whose misses are host-tier promotes
        # doesn't demand a new replica the way a recompute-bound one
        # does. Engines without the host tier — and pre-tier snapshots —
        # report the two values equal, so behavior is unchanged there.
        pressure = snap.get(
            "kv_pressure_two_tier", snap.get("kv_pool_pressure", 0.0)
        )
        if pressure >= config.upscale_kv_pressure:
            return True
        if (snap.get("deadline_miss_rate", 0.0)
                > config.upscale_deadline_miss_rate):
            return True
        p50_bound = getattr(config, "upscale_decode_step_p50_s", None)
        if (p50_bound is not None
                and snap.get("decode_step_p50_s", 0.0) >= p50_bound):
            return True
    return False


def snapshot_is_cold(config: AutoscalingConfig, snap: Mapping) -> bool:
    """One replica is fully idle: nothing queued, nothing decoding, no
    stream parked in ``preempted`` (a parked stream holds no blocks but IS
    pending work — draining the replica would orphan it), and the KV pool
    below the downscale pressure bound (LRU-cached prefix blocks are
    reclaimable, so they don't count against coldness)."""
    return (
        snap.get("queue_depth", 0) == 0
        and snap.get("running", 0) == 0
        and snap.get("prefilling", 0) == 0
        and snap.get("preempted_streams", 0) == 0
        and snap.get("kv_pool_pressure", 0.0) <= config.downscale_kv_pressure
    )


def desired_from_signals(
    config: AutoscalingConfig,
    snapshots: Sequence[Mapping],
    current_num_replicas: int,
) -> int:
    """Desired replicas from per-replica engine snapshots.

    Any hot replica asks for one more; down only when *all* replicas are
    cold. One step per decided period is deliberate: the decider's
    delay-periods debounce sets the ramp rate, and asymmetric up/down
    thresholds (hot is not the complement of cold) give hysteresis so a
    bursty trace can't flap the fleet.
    """
    if not snapshots:
        desired = current_num_replicas
    elif any(snapshot_is_hot(config, s) for s in snapshots):
        desired = current_num_replicas + 1
    elif all(snapshot_is_cold(config, s) for s in snapshots):
        desired = current_num_replicas - 1
    else:
        desired = current_num_replicas
    return max(config.min_replicas, min(config.max_replicas, desired))


def fleet_saturated(
    config: AutoscalingConfig,
    snapshots: Sequence[Mapping],
    current_num_replicas: int,
) -> bool:
    """Cluster-wide admission: shed new work at the router when scaling
    can't help — the fleet is at max_replicas and every replica is both
    hot and already queueing. Requests admitted past this point would sit
    in a waiting queue until the engine's own backpressure (or their
    deadline) killed them; a 503 + Retry-After now is strictly kinder.
    """
    if current_num_replicas < config.max_replicas or not snapshots:
        return False
    return all(
        snapshot_is_hot(config, s) and s.get("queue_depth", 0) > 0
        for s in snapshots
    )


def shed_classes(
    config: AutoscalingConfig,
    snapshots: Sequence[Mapping],
    current_num_replicas: int,
) -> tuple:
    """Which priority classes to reject at admission, batch-first.

    Graduated degradation between "serve everything" and the binary
    fleet_saturated shed: once the fleet is at max_replicas and every
    replica reports ``preempt_exhausted`` (pressure holds but no running
    stream is outranked by a waiter — preemption has no more room to
    make), new low-priority work is doomed to park or starve, so reject
    it at the router instead. The preemption thresholds sit BELOW the
    upscale/hot thresholds, so this fires in the band before
    fleet_saturated does — batch sheds first; the default class joins
    only when every replica also shows default-class backlog
    (``queue_depth_by_class``); interactive is only ever shed by the
    full fleet_saturated signal, which supersedes this one.
    """
    if fleet_saturated(config, snapshots, current_num_replicas):
        return ("batch", "default", "interactive")
    if current_num_replicas < config.max_replicas or not snapshots:
        return ()
    exhausted = all(s.get("preempt_exhausted", False) for s in snapshots)
    if not exhausted:
        return ()
    default_backlogged = all(
        (s.get("queue_depth_by_class") or {}).get("default", 0) > 0
        for s in snapshots
    )
    return ("batch", "default") if default_backlogged else ("batch",)


class AutoscalingDecider:
    """Debounces policy output: act only after N consecutive periods agree
    (reference: upscale_delay_s/downscale_delay_s)."""

    def __init__(self, config: AutoscalingConfig):
        self.config = config
        self._pending_direction = 0
        self._streak = 0

    def decide(self, total_ongoing: float, current: int) -> int:
        """Request-count policy (generic deployments)."""
        desired = calculate_desired_num_replicas(self.config, total_ongoing, current)
        return self._debounce(desired, current)

    def decide_from_signals(self, snapshots: Sequence[Mapping], current: int) -> int:
        """Engine-signal policy (serve.llm deployments)."""
        desired = desired_from_signals(self.config, snapshots, current)
        return self._debounce(desired, current)

    def _debounce(self, desired: int, current: int) -> int:
        direction = (desired > current) - (desired < current)
        if direction == 0:
            # A settled period breaks any pending streak entirely: clearing
            # only _streak (and not _pending_direction) would let a later
            # tick in the same direction inherit the stale direction state.
            self._streak = 0
            self._pending_direction = 0
            return current
        if direction != self._pending_direction:
            self._pending_direction = direction
            self._streak = 1
        else:
            self._streak += 1
        needed = (
            self.config.upscale_delay_periods
            if direction > 0
            else self.config.downscale_delay_periods
        )
        if self._streak >= needed:
            self._streak = 0
            self._pending_direction = 0
            return desired
        return current
