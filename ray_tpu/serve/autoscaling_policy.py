"""Replica autoscaling policy — pure math, unit-testable.

Equivalent of the reference's serve autoscaling policy
(reference: python/ray/serve/_private/autoscaling_policy.py:12
calculate_desired_num_replicas, :78 smoothing/bounds).
"""
from __future__ import annotations

import math

from ray_tpu.serve.config import AutoscalingConfig


def calculate_desired_num_replicas(
    config: AutoscalingConfig,
    total_ongoing_requests: float,
    current_num_replicas: int,
) -> int:
    """Desired replicas from aggregate in-flight load.

    desired = current * (per-replica load / target), smoothed separately for
    up- and down-scaling, clamped to [min, max].
    """
    if current_num_replicas <= 0:
        # scale-from-zero: enough replicas to cover the queue at target load
        raw = total_ongoing_requests / max(config.target_ongoing_requests, 1e-9)
        desired = math.ceil(raw)
    else:
        per_replica = total_ongoing_requests / current_num_replicas
        error_ratio = per_replica / max(config.target_ongoing_requests, 1e-9)
        smoothing = (
            config.upscale_smoothing_factor
            if error_ratio >= 1.0
            else config.downscale_smoothing_factor
        )
        # move a `smoothing` fraction of the way toward the raw target
        raw = current_num_replicas * (1.0 + (error_ratio - 1.0) * smoothing)
        desired = math.ceil(raw) if error_ratio >= 1.0 else math.floor(raw)
    return max(config.min_replicas, min(config.max_replicas, desired))


class AutoscalingDecider:
    """Debounces policy output: act only after N consecutive periods agree
    (reference: upscale_delay_s/downscale_delay_s)."""

    def __init__(self, config: AutoscalingConfig):
        self.config = config
        self._pending_direction = 0
        self._streak = 0

    def decide(self, total_ongoing: float, current: int) -> int:
        desired = calculate_desired_num_replicas(self.config, total_ongoing, current)
        direction = (desired > current) - (desired < current)
        if direction == 0:
            self._streak = 0
            return current
        if direction != self._pending_direction:
            self._pending_direction = direction
            self._streak = 1
        else:
            self._streak += 1
        needed = (
            self.config.upscale_delay_periods
            if direction > 0
            else self.config.downscale_delay_periods
        )
        if self._streak >= needed:
            self._streak = 0
            return desired
        return current
