"""ray_tpu.serve.llm — continuous-batching LLM inference on the Serve layer.

The flagship serving workload (ROADMAP north star: token streaming to
millions of users): a vLLM-style engine — paged KV cache + prefill/decode
interleaving — built TPU-first, meaning every jitted shape is drawn from a
closed bucket set so the XLA compile cache stays bounded (arxiv
2011.03641; SURVEY.md §7). Pieces:

- kv_cache.py — block allocator + preallocated cache arrays + block tables
- decode.py   — jitted prefill / decode / verify steps per model family
- drafter.py  — host-side draft proposal for speculative decoding
- executor.py — ModelExecutor seam: single-device or tp/fsdp-sharded
- engine.py   — the continuous-batching scheduler (admission, join/evict)
- api.py      — LLMDeployment: the engine as a streaming Serve deployment

See docs/SERVING_LLM.md for the design.
"""
from ray_tpu.exceptions import (
    DeadlineExceededError,
    EngineDiedError,
    EngineOverloadedError,
    RequestCancelledError,
)
from ray_tpu.serve.config import ModelParallelConfig
from ray_tpu.serve.llm.api import LLMDeployment, build_llm_app, stream_tokens
from ray_tpu.serve.llm.drafter import Drafter, NGramDrafter, build_drafter
from ray_tpu.serve.llm.engine import EngineConfig, LLMEngine, SamplingParams
from ray_tpu.serve.llm.executor import (
    ModelExecutor,
    ShardedExecutor,
    SingleDeviceExecutor,
    build_executor,
)
from ray_tpu.serve.llm.kv_cache import KVCacheConfig, PagedKVCache

__all__ = [
    "DeadlineExceededError",
    "Drafter",
    "EngineConfig",
    "EngineDiedError",
    "EngineOverloadedError",
    "KVCacheConfig",
    "LLMDeployment",
    "LLMEngine",
    "ModelExecutor",
    "ModelParallelConfig",
    "NGramDrafter",
    "PagedKVCache",
    "RequestCancelledError",
    "SamplingParams",
    "ShardedExecutor",
    "SingleDeviceExecutor",
    "build_drafter",
    "build_executor",
    "stream_tokens",
    "build_llm_app",
]
