"""ray_tpu.serve.llm — continuous-batching LLM inference on the Serve layer.

The flagship serving workload (ROADMAP north star: token streaming to
millions of users): a vLLM-style engine — paged KV cache + prefill/decode
interleaving — built TPU-first, meaning every jitted shape is drawn from a
closed bucket set so the XLA compile cache stays bounded (arxiv
2011.03641; SURVEY.md §7). Pieces:

- kv_cache.py — block allocator + preallocated cache arrays + block tables
- decode.py   — jitted prefill / decode / verify steps per model family
- drafter.py  — host-side draft proposal for speculative decoding
- executor.py — ModelExecutor seam: single-device or tp/fsdp-sharded
- engine.py   — the continuous-batching scheduler (admission, join/evict)
- kv_transfer.py — versioned KV-block wire format for the disaggregated
  prefill→decode handoff over the object plane
- api.py      — LLMDeployment: the engine as a streaming Serve deployment

See docs/SERVING_LLM.md for the design.

Exports resolve lazily (PEP 562): the engine/decode modules pull in jax,
and light consumers — notably the serve controller, which imports
``serve.llm.obs`` for the one-clock rule when aggregating autoscaling
snapshots — must not pay that import in their process.
"""
from __future__ import annotations

import importlib

_EXPORTS = {
    "DeadlineExceededError": "ray_tpu.exceptions",
    "EngineDiedError": "ray_tpu.exceptions",
    "EngineOverloadedError": "ray_tpu.exceptions",
    "RequestCancelledError": "ray_tpu.exceptions",
    "ModelParallelConfig": "ray_tpu.serve.config",
    "LLMDeployment": "ray_tpu.serve.llm.api",
    "build_llm_app": "ray_tpu.serve.llm.api",
    "stream_tokens": "ray_tpu.serve.llm.api",
    "Drafter": "ray_tpu.serve.llm.drafter",
    "NGramDrafter": "ray_tpu.serve.llm.drafter",
    "build_drafter": "ray_tpu.serve.llm.drafter",
    "EngineConfig": "ray_tpu.serve.llm.engine",
    "LLMEngine": "ray_tpu.serve.llm.engine",
    "SamplingParams": "ray_tpu.serve.llm.engine",
    "ModelExecutor": "ray_tpu.serve.llm.executor",
    "ShardedExecutor": "ray_tpu.serve.llm.executor",
    "SingleDeviceExecutor": "ray_tpu.serve.llm.executor",
    "build_executor": "ray_tpu.serve.llm.executor",
    "KVCacheConfig": "ray_tpu.serve.llm.kv_cache",
    "PagedKVCache": "ray_tpu.serve.llm.kv_cache",
    "KVLayout": "ray_tpu.serve.llm.kv_transfer",
    "KVTransferError": "ray_tpu.serve.llm.kv_transfer",
    "handoff_object_id": "ray_tpu.serve.llm.kv_transfer",
    "pack_blocks": "ray_tpu.serve.llm.kv_transfer",
    "unpack_blocks": "ray_tpu.serve.llm.kv_transfer",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    value = getattr(importlib.import_module(mod), name)
    globals()[name] = value  # cache for subsequent lookups
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
