"""LLMDeployment — the engine wrapped as a streaming Serve deployment.

One engine per replica; each HTTP/gRPC/handle call becomes one engine
request, and because the replica runs up to max_ongoing_requests method
threads concurrently (serve/replica.py), concurrent callers' sequences
CONTINUOUSLY BATCH inside the shared engine — the scheduler interleaves
them at the decode-step level, not the request level. Tokens stream out
through every existing ingress: the DeploymentHandle generator path, HTTP
server-sent events, and the gRPC server-streaming RPC (all three are
exercised by examples/serve_streaming_llm.py).

Prompts are token-id lists, or strings encoded with the built-in
byte-level tokenizer (token = UTF-8 byte value; any vocab >= 256 works) —
a real BPE vocabulary plugs in by passing token ids directly.
"""
from __future__ import annotations

from typing import Any

from ray_tpu.serve.deployment import Application, deployment
from ray_tpu.serve.llm.engine import EngineConfig, LLMEngine, SamplingParams


def encode_text(prompt: str, vocab_size: int) -> list[int]:
    """Byte-level encoding: one token per UTF-8 byte (folded into the
    vocab for the tiny test configs)."""
    return [b % vocab_size for b in prompt.encode("utf-8")]


def decode_token(token: int) -> str:
    """Inverse of encode_text for printable bytes; empty otherwise."""
    return chr(token) if 32 <= token < 127 else ""


@deployment(max_ongoing_requests=8)
class LLMDeployment:
    """Streaming LLM deployment. Bind with an EngineConfig (or dict of its
    fields): ``serve.run(LLMDeployment.bind(EngineConfig(...)))``."""

    def __init__(self, engine_config: EngineConfig | dict | None = None):
        if isinstance(engine_config, dict):
            engine_config = EngineConfig(**engine_config)
        self.engine = LLMEngine(engine_config)

    def __call__(self, payload: dict | None):
        """Generator: one chunk per generated token.

        payload: {"prompt": str | [int], "max_new_tokens"?, "temperature"?,
        "top_k"?, "seed"?}. Chunks: {"token": id, "index": i, "text": str}.
        """
        payload = payload or {}
        prompt = payload.get("prompt", "")
        if isinstance(prompt, str):
            prompt = encode_text(prompt, self.engine.model_cfg.vocab_size)
        sampling = SamplingParams(
            max_new_tokens=int(payload.get("max_new_tokens", 16)),
            temperature=float(payload.get("temperature", 0.0)),
            top_k=int(payload.get("top_k", 0)),
            seed=int(payload.get("seed", 0)),
        )
        stream = self.engine.submit(prompt, sampling)
        for i, tok in enumerate(stream):
            yield {"token": int(tok), "index": i, "text": decode_token(tok)}

    def stats(self) -> dict:
        """Engine introspection (unary method — callable via handle)."""
        return self.engine.stats()


def build_llm_app(
    engine_config: EngineConfig | dict | None = None,
    **deployment_options: Any,
) -> Application:
    """Convenience: ``serve.run(build_llm_app(EngineConfig(...)))``.
    ``deployment_options`` forward to ``.options(...)`` (num_replicas,
    ray_actor_options for TPU chips, ...)."""
    dep = LLMDeployment
    if deployment_options:
        dep = dep.options(**deployment_options)
    return dep.bind(engine_config)
