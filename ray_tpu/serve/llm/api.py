"""LLMDeployment — the engine wrapped as a streaming Serve deployment.

One engine per replica; each HTTP/gRPC/handle call becomes one engine
request, and because the replica runs up to max_ongoing_requests method
threads concurrently (serve/replica.py), concurrent callers' sequences
CONTINUOUSLY BATCH inside the shared engine — the scheduler interleaves
them at the decode-step level, not the request level. Tokens stream out
through every existing ingress: the DeploymentHandle generator path, HTTP
server-sent events, and the gRPC server-streaming RPC (all three are
exercised by examples/serve_streaming_llm.py).

Prompts are token-id lists, or strings encoded with the built-in
byte-level tokenizer (token = UTF-8 byte value; any vocab >= 256 works) —
a real BPE vocabulary plugs in by passing token ids directly.

Repeat traffic with shared prompt prefixes (system prompts, few-shot
headers) is served from the engine's block-granular KV prefix cache —
``stats()`` exposes ``prefix_hit_tokens`` / ``prefix_hit_rate`` /
``prefix_cached_blocks`` / ``prefix_evicted_blocks`` / ``cow_blocks`` per
replica alongside the PR 1/2 fields (docs/SERVING_LLM.md "Prefix caching
& chunked prefill").

Failure semantics (docs/SERVING_LLM.md): every chunk carries
``(request_id, index)`` where ``index`` is the ABSOLUTE token position,
so a client (``stream_tokens`` / ``DeploymentHandle.stream_with_failover``)
can resume a stream on a surviving replica after this one dies: it
re-submits ``prompt`` plus ``prior_tokens`` (the tokens it already has)
and the engine re-prefills; sampling is keyed per (seed, absolute
position) on device, so the resumed stream is byte-identical to an
uninterrupted one by construction — no RNG state to replay.
"""
from __future__ import annotations

import logging
import uuid
from collections import OrderedDict
from typing import Any

from ray_tpu._private import chaos
from ray_tpu.exceptions import EngineOverloadedError
from ray_tpu.serve.deployment import Application, deployment
from ray_tpu.serve.llm import obs
from ray_tpu.serve.llm.engine import EngineConfig, LLMEngine, SamplingParams
from ray_tpu.util import metrics, tracing

logger = logging.getLogger("ray_tpu.serve.llm")

# external request ids whose engine-internal id we remember after the
# stream finished, so request_timeline() works post-hoc
_RECENT_REQUESTS = 512

# Disaggregated prefill/decode handoff knobs (docs/SERVING_LLM.md
# "Disaggregated prefill/decode"): how long a prefill replica keeps a
# sealed-but-unclaimed KV object before its periodic sweep deletes it
# (clients sweep their own attempts when the stream ends; this TTL is
# the backstop for clients that died mid-handoff), how long the decode
# side waits on a fetch before falling back to local prefill, and how
# long the client waits on one seal attempt.
_HANDOFF_TTL_S = 120.0
_HANDOFF_FETCH_TIMEOUT_S = 10.0
_HANDOFF_SEAL_TIMEOUT_S = 30.0


def encode_text(prompt: str, vocab_size: int) -> list[int]:
    """Byte-level encoding: one token per UTF-8 byte (folded into the
    vocab for the tiny test configs)."""
    return [b % vocab_size for b in prompt.encode("utf-8")]


def decode_token(token: int) -> str:
    """Inverse of encode_text for printable bytes; empty otherwise."""
    return chr(token) if 32 <= token < 127 else ""


@deployment(max_ongoing_requests=8)
class LLMDeployment:
    """Streaming LLM deployment. Bind with an EngineConfig (or dict of its
    fields): ``serve.run(LLMDeployment.bind(EngineConfig(...)))``.

    Multi-chip replicas: pass ``mesh=`` (a ``ModelParallelConfig``, a
    ``parallel.MeshSpec``, a built ``jax.sharding.Mesh``, or a dict of
    axis sizes) — or set ``tp``/``fsdp`` on the EngineConfig itself — and
    the replica's engine runs the tp/fsdp ShardedExecutor over that mesh
    (docs/SERVING_LLM.md "Sharded serving"). Defaults stay single-device;
    request payloads, streaming, failover, and the prefix cache are
    identical either way — a stream started on a sharded replica resumes
    byte-identically on a single-chip one and vice versa."""

    def __init__(
        self,
        engine_config: EngineConfig | dict | None = None,
        mesh: Any = None,
        prefill: Any = None,
    ):
        if isinstance(engine_config, dict):
            engine_config = EngineConfig(**engine_config)
        if mesh is not None:
            import dataclasses

            engine_config = dataclasses.replace(
                engine_config or EngineConfig(), mesh=mesh
            )
        self.engine = LLMEngine(engine_config)
        # Disaggregated serving: binding a prefill Application here makes
        # serve.run deploy both pools as one app (Application.flatten);
        # the handle itself is only introspected — the handoff state
        # machine runs client-side in stream_tokens.
        self._prefill = prefill
        # sealed handoff objects this (prefill) replica still owns:
        # object-id hex -> obs.clock() seal time, swept by TTL
        self._sealed: OrderedDict[str, float] = OrderedDict()
        self._handoff_sealed_total = 0
        self._handoff_landed_blocks = 0
        self._handoff_fallbacks = 0
        self._m_handoff_blocks = metrics.counter(
            "llm_handoff_blocks",
            "KV blocks landed on this replica from handoff payloads",
        )
        self._m_handoff_retries = metrics.counter(
            "llm_handoff_retries",
            "Handoff attempts that were retried or fell back to "
            "decode-local prefill",
        )
        # external request_id -> engine-internal id, for cancel()
        self._active: dict[str, Any] = {}
        # same mapping, kept (bounded) after completion for
        # request_timeline() lookups on finished streams
        self._recent: OrderedDict[str, Any] = OrderedDict()
        self._resumed_total = 0
        self._m_resumed = metrics.counter(
            "llm_requests_resumed",
            "Streams resumed on this replica after another replica died",
        )
        # graceful-drain latch (controller-driven scale-down): a draining
        # replica admits nothing new; in-flight streams finish or hand off
        self._draining = False

    def __call__(self, payload: dict | None):
        """Generator: one chunk per generated token.

        payload: {"prompt": str | [int], "max_new_tokens"?, "temperature"?,
        "top_k"?, "top_p"?, "seed"?, "request_id"?, "deadline_s"?,
        "prior_tokens"?, "response_format"?, "stop"?, "priority"?}.
        ``priority`` is the scheduling class ("interactive" | "default" |
        "batch" — docs/SERVING_LLM.md "Priority & preemption"); the
        proxies inject it from the ``x-ray-tpu-priority`` header/metadata
        key. It orders preemption and class-aware shedding and never
        changes tokens.
        ``response_format`` selects grammar-constrained decoding
        (serve/llm/structured.py): ``"json"``/``"json_object"`` or an
        OpenAI-shaped dict ({"type": "json_schema", "schema": ...} /
        {"type": "regex", "pattern": ...}); invalid or unsatisfiable
        grammars fail the request with a ``ValueError`` (HTTP 400 /
        gRPC INVALID_ARGUMENT at the proxies). ``stop`` is a list of
        stop sequences — strings (byte-level encoded like the prompt)
        or token-id lists — that terminate the stream once emitted.
        Chunks: {"request_id": str, "token": id, "index": i, "text": str}
        where ``index`` is absolute — a resumed stream continues the
        numbering of the stream it replaces.
        """
        if self._draining:
            # Scale-down marked this replica draining; the routing table
            # already excludes it, so only a dispatch racing the table
            # refresh lands here. EngineOverloadedError is the retryable
            # "go elsewhere" signal: failover resumes re-dispatch to a
            # survivor, fresh requests get 503 + Retry-After.
            raise EngineOverloadedError(
                "replica is draining for scale-down; retry another replica"
            )
        payload = payload or {}
        prompt = payload.get("prompt", "")
        if isinstance(prompt, str):
            prompt = encode_text(prompt, self.engine.model_cfg.vocab_size)
        prompt = [int(t) for t in prompt]
        request_id = str(payload.get("request_id") or uuid.uuid4().hex)
        prior = [int(t) for t in payload.get("prior_tokens") or ()]
        max_new = int(payload.get("max_new_tokens", 16))
        if prior:
            self._resumed_total += 1
            self._m_resumed.inc()
            if len(prior) >= max_new:
                return  # the dead replica already delivered everything
        handoff = payload.get("kv_handoff")
        if handoff:
            # Land prefilled KV blocks from the object plane BEFORE
            # submit, so admission sees the prefix hit. Failure of any
            # kind degrades to decode-local chunked prefill — a torn
            # handoff must never become a dead stream.
            self._land_handoff(
                prompt, handoff, tag=payload.get("chaos_tag")
            )
        deadline_s = payload.get("deadline_s")
        stop = []
        for seq in payload.get("stop") or ():
            if isinstance(seq, str):
                seq = encode_text(seq, self.engine.model_cfg.vocab_size)
            stop.append(tuple(int(t) for t in seq))
        sampling = SamplingParams(
            max_new_tokens=max_new - len(prior),
            temperature=float(payload.get("temperature", 0.0)),
            top_k=int(payload.get("top_k", 0)),
            top_p=float(payload.get("top_p", 1.0)),
            seed=int(payload.get("seed", 0)),
            deadline_s=float(deadline_s) if deadline_s is not None else None,
            start_index=len(prior),
            structured=payload.get("response_format"),
            stop=tuple(stop),
            priority=str(payload.get("priority", "default")),
        )
        # the replica method runs inside a task_span when the caller was
        # traced — hand that context to the engine so its phase spans join
        # the same trace, and stamp the trace id on every chunk so a
        # resumed stream can assert trace continuity across replicas
        trace_ctx = tracing.current_context()
        trace_id = trace_ctx["trace_id"] if trace_ctx else None
        stream = self.engine.submit(
            prompt + prior, sampling, trace_ctx=trace_ctx
        )
        self._active[request_id] = stream.request_id
        self._recent[request_id] = stream.request_id
        while len(self._recent) > _RECENT_REQUESTS:
            self._recent.popitem(last=False)
        try:
            for i, tok in enumerate(stream):
                index = len(prior) + i
                chunk = {
                    "request_id": request_id,
                    "token": int(tok),
                    "index": index,
                    "text": decode_token(tok),
                }
                if trace_id is not None:
                    chunk["trace_id"] = trace_id
                yield chunk
                chaos.fire(
                    "llm.token",
                    index=index,
                    resumed=bool(prior),
                    tag=payload.get("chaos_tag"),
                )
        finally:
            self._active.pop(request_id, None)

    def cancel(self, request_id: str) -> bool:
        """Evict ``request_id`` and free its KV blocks now. Idempotent and
        safe to broadcast: replicas not serving the stream return False."""
        internal = self._active.get(str(request_id))
        if internal is None:
            return False
        return self.engine.cancel(internal)

    def check_health(self) -> None:
        """Controller health-check hook: a failed engine (step raised or
        watchdog fired) reports unhealthy so the replica gets replaced."""
        if self.engine.failed:
            raise RuntimeError("llm engine failed; replica must be replaced")

    def stats(self) -> dict:
        """Engine introspection (unary method — callable via handle)."""
        out = self.engine.stats()
        out["requests_resumed"] = self._resumed_total
        return out

    def request_timeline(self, request_id: str) -> dict | None:
        """Phase timeline of one EXTERNAL request id — live or recently
        finished on this replica; None if this replica never served it
        (broadcast to find the owner, like cancel)."""
        internal = self._active.get(str(request_id))
        if internal is None:
            internal = self._recent.get(str(request_id))
        if internal is None:
            return None
        return self.engine.request_timeline(internal)

    def debug_dump(self) -> dict:
        """Flight-recorder ring + engine/cache stats (the payload behind
        the proxy's /debug/llm endpoint)."""
        out = self.engine.debug_dump()
        out["requests_resumed"] = self._resumed_total
        out["draining"] = self._draining
        out["handoff"] = self.handoff_stats()
        return out

    # ---------------- autoscaling & graceful drain ----------------

    def autoscaling_snapshot(self) -> dict:
        """Engine saturation signals for the controller's autoscaler
        (docs/SERVING_LLM.md "Autoscaling & graceful drain"). The
        ``llm.snapshot`` chaos point sits here so the load harness can
        delay/jitter snapshot reporting deterministically."""
        chaos.fire("llm.snapshot")
        out = self.engine.autoscaling_snapshot()
        out["draining"] = self._draining
        out["active_streams"] = len(self._active)
        return out

    def prepare_drain(self) -> dict:
        """Controller scale-down hook: stop admitting, keep serving.

        After this returns, new ``__call__`` dispatches are refused with
        ``EngineOverloadedError`` while every in-flight stream keeps
        decoding; the controller polls ``drain_status`` and finishes (or
        kills — the failover path hands the streams to survivors
        byte-identically) once the replica is idle or the drain deadline
        expires. Idempotent."""
        self._draining = True
        chaos.fire("replica_drain", active=len(self._active))
        return self.drain_status()

    def drain_status(self) -> dict:
        return {
            "draining": self._draining,
            "active_streams": len(self._active),
        }

    def finish_drain(self) -> dict:
        """Terminal drain step, called by the controller once no streams
        are active: returns every KV block (allocations, reservations,
        quarantine, prefix cache) to the pool via the engine's
        ``release_all`` shutdown path and reports the final accounting so
        the caller can assert the pool is leak-free before the actor is
        killed."""
        self.engine.shutdown()
        snap = self.engine.cache.debug_snapshot()
        return {
            "released": True,
            "leaked_blocks": snap["used_blocks"],
            "cache": snap,
        }

    # ---------------- disaggregated prefill/decode handoff ----------------

    def prefill_export(self, payload: dict | None) -> dict | None:
        """PREFILL-pool entrypoint: run the payload's prompt through
        normal (chunked, prefix-cached) prefill, serialize its full
        prompt blocks with the kv_transfer wire format, seal them into
        the object store under a deterministic per-attempt id, and
        return the manifest the client forwards to the decode pool.

        Returns None when there is nothing worth handing off (prompt
        shorter than one block, or no blocks resident after prefill) —
        the client then simply dispatches without ``kv_handoff`` and the
        decode replica prefills locally. Idempotent per (request_id,
        attempt): re-driving a seal writes the same object id, and an
        already-sealed object is left as-is."""
        from ray_tpu._private.worker import global_worker_or_none
        from ray_tpu.serve.llm import kv_transfer

        if self._draining:
            raise EngineOverloadedError(
                "replica is draining for scale-down; retry another replica"
            )
        payload = payload or {}
        prompt = payload.get("prompt", "")
        if isinstance(prompt, str):
            prompt = encode_text(prompt, self.engine.model_cfg.vocab_size)
        prompt = [int(t) for t in prompt]
        request_id = str(payload.get("request_id") or uuid.uuid4().hex)
        attempt = int(payload.get("attempt", 0))
        if attempt > 0:
            self._m_handoff_retries.inc()
        self._sweep_sealed()
        bs = self.engine.cache.cfg.block_size
        worker = global_worker_or_none()
        if len(prompt) < bs or worker is None:
            return None
        # Normal engine path with a 1-token budget: chunked prefill at
        # true positions writes the prompt's KV and registers every full
        # block in the prefix cache; the sampled token is discarded.
        # Traced callers (this method runs inside the rt_call task span)
        # get a handoff.seal span covering prefill through store put —
        # and the engine submit below inherits the active span, so the
        # PREFILL pool's engine.* spans join the same trace tree.
        sampling = SamplingParams(
            max_new_tokens=1, seed=int(payload.get("seed", 0))
        )
        with tracing.span_if_active(
            "handoff.seal", request_id=request_id, attempt=attempt,
        ):
            stream = self.engine.submit(prompt, sampling)
            for _ in stream:
                pass
            chaos.fire(
                "llm.handoff.seal",
                request_id=request_id,
                attempt=attempt,
                tag=payload.get("chaos_tag"),
            )
            records = self.engine.export_prefix(prompt)
            if not records:
                return None
            wire = kv_transfer.pack_blocks(
                self.engine.kv_layout(), records,
                prefix_tokens=len(records) * bs,
            )
            oid = kv_transfer.handoff_object_id(request_id, attempt)
            # pin=False: an orphaned handoff object stays LRU-evictable in
            # the store even if every sweeper dies
            worker.put_object(oid, wire, pin=False)
        self._sealed[oid.hex()] = obs.clock()
        self._handoff_sealed_total += 1
        return {
            "object_id": oid.hex(),
            "request_id": request_id,
            "attempt": attempt,
            "prefix_tokens": len(records) * bs,
            "num_blocks": len(records),
        }

    def _sweep_sealed(self) -> int:
        """Delete sealed handoff objects older than the TTL (leak sweep
        for clients that died between seal and stream end). Runs at the
        top of every ``prefill_export``; -> objects swept."""
        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.worker import global_worker_or_none

        worker = global_worker_or_none()
        if worker is None:
            return 0
        now = obs.clock()
        swept = 0
        while self._sealed:
            oid_hex, sealed_at = next(iter(self._sealed.items()))
            if now - sealed_at < _HANDOFF_TTL_S:
                break
            self._sealed.popitem(last=False)
            try:
                worker.store.delete(ObjectID.from_hex(oid_hex))
            except (ConnectionError, OSError) as e:
                # store daemon gone — nothing to leak into, but the
                # sweep must never take a prefill replica down
                logger.warning("handoff sweep of %s failed: %s", oid_hex, e)
            swept += 1
        return swept

    def _land_handoff(self, prompt, manifest: dict, tag=None) -> int:
        """DECODE-pool half: fetch the manifest's object, verify it, and
        adopt its blocks into this engine's prefix cache so the upcoming
        submit scores a full prefix hit. Every failure mode — evicted or
        lost object, fetch timeout, wire corruption, layout mismatch,
        injected chaos — degrades to decode-local prefill (return 0),
        never a dead stream."""
        import ray_tpu
        from ray_tpu._private.ids import ObjectID
        from ray_tpu._private.object_ref import ObjectRef
        from ray_tpu._private.worker import global_worker_or_none
        from ray_tpu.exceptions import GetTimeoutError, ObjectLostError
        from ray_tpu.serve.llm import kv_transfer

        request_id = manifest.get("request_id") or "?"
        attempt = int(manifest.get("attempt", 0))
        try:
            # traced requests see the decode-side handoff halves as
            # handoff.fetch / handoff.land spans (attempt-tagged, so a
            # retried handoff is visibly attempt>0 in the trace tree)
            with tracing.span_if_active(
                "handoff.fetch", request_id=request_id, attempt=attempt,
            ):
                chaos.fire("llm.handoff.fetch", attempt=attempt, tag=tag)
                if global_worker_or_none() is None:
                    raise kv_transfer.KVTransferError(
                        "no object plane in this process"
                    )
                oid = ObjectID.from_hex(str(manifest["object_id"]))
                wire = ray_tpu.get(
                    ObjectRef(oid), timeout=_HANDOFF_FETCH_TIMEOUT_S
                )
            with tracing.span_if_active(
                "handoff.land", request_id=request_id, attempt=attempt,
            ):
                chaos.fire("llm.handoff.land", attempt=attempt, tag=tag)
                layout, _, records = kv_transfer.unpack_blocks(wire)
                if layout != self.engine.kv_layout():
                    raise kv_transfer.KVTransferError(
                        f"layout mismatch: payload {layout} vs engine "
                        f"{self.engine.kv_layout()}"
                    )
                landed = self.engine.adopt_prefix(prompt, records)
            self._handoff_landed_blocks += landed
            if landed:
                self._m_handoff_blocks.inc(landed)
            return landed
        except (
            ObjectLostError,
            GetTimeoutError,
            kv_transfer.KVTransferError,
            chaos.ChaosFault,
            ConnectionError,
            KeyError,
            ValueError,
        ) as e:
            self._handoff_fallbacks += 1
            self._m_handoff_retries.inc()
            logger.warning(
                "KV handoff for request %s failed (%s: %s); falling back "
                "to decode-local prefill", request_id, type(e).__name__, e,
            )
            return 0

    def handoff_stats(self) -> dict:
        """Per-replica handoff accounting (unary, broadcastable): sealed
        objects still owned, blocks landed, fallbacks taken."""
        return {
            "sealed_live": len(self._sealed),
            "sealed_total": self._handoff_sealed_total,
            "landed_blocks": self._handoff_landed_blocks,
            "fallbacks": self._handoff_fallbacks,
            "adopted_blocks": self.engine.cache.stats.adopted_blocks,
        }


def stream_tokens(handle, payload: dict, *, max_failovers: int = 2,
                  prefill_handle=None, handoff_retries: int = 2):
    """Stream token chunks from an LLMDeployment handle with automatic
    mid-stream failover: if the serving replica dies, re-submit to a
    survivor with ``prior_tokens`` set to everything already received.
    Deterministic sampling makes the joined stream byte-identical to an
    uninterrupted run. Returns an iterator of chunk dicts.

    Disaggregated serving: pass ``prefill_handle`` (the LLMPrefill pool)
    and the prompt is prefilled there first — the sealed KV manifest
    rides in the payload as ``kv_handoff`` and the decode replica lands
    the blocks instead of prefilling. The seal loop is an idempotent
    retry state machine: a prefill replica killed mid-handoff is
    excluded and the next attempt (a NEW deterministic object id) runs
    on a survivor; when the pool is overloaded or ``handoff_retries``
    attempts die, the stream degrades to decode-local prefill. Every
    attempt's object id — delivered or not — is swept from the store
    when the stream ends, so dead handoffs cannot leak sealed objects.
    Byte-identity is unconditional: landed blocks are bit-exact KV for
    the same tokens, and sampling is keyed (seed, position)."""
    payload = dict(payload)
    payload.setdefault("request_id", uuid.uuid4().hex)
    attempt_oids: list[str] = []
    if prefill_handle is not None:
        manifest = _seal_handoff(
            prefill_handle, payload, attempt_oids, retries=handoff_retries
        )
        if manifest is not None:
            payload["kv_handoff"] = manifest

    def resume(chunks):
        # the resumed payload keeps kv_handoff: a decode survivor
        # re-lands the same sealed blocks (adopt is idempotent) before
        # re-prefilling whatever is missing
        resumed = dict(payload)
        resumed["prior_tokens"] = [c["token"] for c in chunks]
        return resumed

    stream = handle.stream_with_failover(
        payload, resume=resume, max_failovers=max_failovers
    )
    if not attempt_oids:
        return stream
    return _sweeping_stream(stream, attempt_oids)


def _seal_handoff(prefill_handle, payload: dict, attempt_oids: list[str],
                  *, retries: int = 2) -> dict | None:
    """Drive prefill_export attempts until one seals, the pool sheds, or
    the attempts run out. Records every attempt's deterministic object
    id in ``attempt_oids`` (even for attempts that died before replying)
    so the caller can leak-sweep them all; returns the manifest or None
    for decode-local fallback."""
    from ray_tpu.exceptions import ActorError, WorkerCrashedError
    from ray_tpu.serve.llm import kv_transfer

    request_id = str(payload["request_id"])
    req = {
        k: v for k, v in payload.items()
        if k not in ("prior_tokens", "kv_handoff")
    }
    exclude: set[str] = set()
    for attempt in range(max(1, retries + 1)):
        req = dict(req, attempt=attempt)
        attempt_oids.append(
            kv_transfer.handoff_object_id(request_id, attempt).hex()
        )
        resp = None
        try:
            resp = prefill_handle._router.call(
                "prefill_export", (req,), {}, exclude=frozenset(exclude)
            )
            return resp.result(timeout=_HANDOFF_SEAL_TIMEOUT_S)
        except EngineOverloadedError:
            # prefill pool saturated or draining — decode-local prefill
            # is the designed pressure valve, not an error
            logger.debug(
                "prefill pool overloaded for request %s; using "
                "decode-local prefill", request_id,
            )
            return None
        except (ActorError, WorkerCrashedError, ConnectionError,
                TimeoutError) as e:
            aid = getattr(resp, "replica_actor_id", None)
            if aid:
                exclude.add(aid)
            logger.warning(
                "prefill handoff attempt %d for request %s failed "
                "(%s: %s); %s", attempt, request_id, type(e).__name__, e,
                "retrying on a survivor" if attempt < retries
                else "falling back to decode-local prefill",
            )
    return None


def _sweeping_stream(stream, attempt_oids: list[str]):
    """Yield the stream, then delete every handoff attempt object —
    delivered, orphaned by a killed prefill replica, or never created
    (delete is idempotent). Runs on normal completion AND on failure/
    generator close, so a dead client path can't leak sealed objects."""
    try:
        yield from stream
    finally:
        _sweep_attempts(attempt_oids)


def _sweep_attempts(attempt_oids: list[str]) -> None:
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.worker import global_worker_or_none

    worker = global_worker_or_none()
    if worker is None:
        return
    for oid_hex in attempt_oids:
        try:
            worker.store.delete(ObjectID.from_hex(oid_hex))
        except (ConnectionError, OSError) as e:
            logger.debug("handoff sweep of %s failed: %s", oid_hex, e)


def build_llm_app(
    engine_config: EngineConfig | dict | None = None,
    *,
    mesh: Any = None,
    tp: int = 1,
    fsdp: int = 1,
    speculative_k: int | None = None,
    drafter: Any = None,
    prefill_replicas: int = 0,
    prefill_options: dict | None = None,
    **deployment_options: Any,
) -> Application:
    """Convenience: ``serve.run(build_llm_app(EngineConfig(...)))``.
    ``deployment_options`` forward to ``.options(...)`` (num_replicas,
    ray_actor_options for TPU chips, ...).

    ``mesh``/``tp``/``fsdp`` select the per-replica model-parallel
    layout (they override the EngineConfig fields of the same names);
    the defaults keep every replica single-device. ``speculative_k`` /
    ``drafter`` likewise override the engine's speculative-decoding
    knobs (docs/SERVING_LLM.md "Speculative decoding") — committed
    streams stay byte-identical with speculation on or off, so mixed
    fleets (some replicas speculative, some not) fail over freely.

    ``prefill_replicas > 0`` builds DISAGGREGATED serving: a second
    deployment named ``LLMPrefill`` (``pool_role="prefill"``) joins the
    decode deployment (named ``LLMDecode``, ``pool_role="decode"``) in
    the same app, and clients pass
    ``serve.get_deployment_handle("LLMPrefill", app)`` as
    ``stream_tokens(..., prefill_handle=)`` to route prefill there.
    ``prefill_options`` overrides the prefill pool's deployment config
    (e.g. its own ``autoscaling_config`` — typically
    ``signal_mode="prefill"``, with the decode pool on
    ``signal_mode="decode"`` — so the two pools scale on disjoint
    signals and drain independently)."""
    overrides: dict = {}
    if mesh is not None or tp != 1 or fsdp != 1:
        overrides.update(mesh=mesh, tp=tp, fsdp=fsdp)
    if speculative_k is not None:
        overrides["speculative_k"] = int(speculative_k)
    if drafter is not None:
        overrides["drafter"] = drafter
    if overrides:
        import dataclasses

        if isinstance(engine_config, dict):
            engine_config = EngineConfig(**engine_config)
        engine_config = dataclasses.replace(
            engine_config or EngineConfig(), **overrides
        )
    if prefill_replicas > 0:
        popts = {
            "name": "LLMPrefill",
            "num_replicas": int(prefill_replicas),
            "pool_role": "prefill",
            **(prefill_options or {}),
        }
        prefill_app = LLMDeployment.options(**popts).bind(engine_config)
        dopts = {"name": "LLMDecode", "pool_role": "decode",
                 **deployment_options}
        return LLMDeployment.options(**dopts).bind(
            engine_config, prefill=prefill_app
        )
    dep = LLMDeployment
    if deployment_options:
        dep = dep.options(**deployment_options)
    return dep.bind(engine_config)
