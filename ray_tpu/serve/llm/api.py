"""LLMDeployment — the engine wrapped as a streaming Serve deployment.

One engine per replica; each HTTP/gRPC/handle call becomes one engine
request, and because the replica runs up to max_ongoing_requests method
threads concurrently (serve/replica.py), concurrent callers' sequences
CONTINUOUSLY BATCH inside the shared engine — the scheduler interleaves
them at the decode-step level, not the request level. Tokens stream out
through every existing ingress: the DeploymentHandle generator path, HTTP
server-sent events, and the gRPC server-streaming RPC (all three are
exercised by examples/serve_streaming_llm.py).

Prompts are token-id lists, or strings encoded with the built-in
byte-level tokenizer (token = UTF-8 byte value; any vocab >= 256 works) —
a real BPE vocabulary plugs in by passing token ids directly.

Repeat traffic with shared prompt prefixes (system prompts, few-shot
headers) is served from the engine's block-granular KV prefix cache —
``stats()`` exposes ``prefix_hit_tokens`` / ``prefix_hit_rate`` /
``prefix_cached_blocks`` / ``prefix_evicted_blocks`` / ``cow_blocks`` per
replica alongside the PR 1/2 fields (docs/SERVING_LLM.md "Prefix caching
& chunked prefill").

Failure semantics (docs/SERVING_LLM.md): every chunk carries
``(request_id, index)`` where ``index`` is the ABSOLUTE token position,
so a client (``stream_tokens`` / ``DeploymentHandle.stream_with_failover``)
can resume a stream on a surviving replica after this one dies: it
re-submits ``prompt`` plus ``prior_tokens`` (the tokens it already has)
and the engine re-prefills; sampling is keyed per (seed, absolute
position) on device, so the resumed stream is byte-identical to an
uninterrupted one by construction — no RNG state to replay.
"""
from __future__ import annotations

import uuid
from collections import OrderedDict
from typing import Any

from ray_tpu._private import chaos
from ray_tpu.exceptions import EngineOverloadedError
from ray_tpu.serve.deployment import Application, deployment
from ray_tpu.serve.llm.engine import EngineConfig, LLMEngine, SamplingParams
from ray_tpu.util import metrics, tracing

# external request ids whose engine-internal id we remember after the
# stream finished, so request_timeline() works post-hoc
_RECENT_REQUESTS = 512


def encode_text(prompt: str, vocab_size: int) -> list[int]:
    """Byte-level encoding: one token per UTF-8 byte (folded into the
    vocab for the tiny test configs)."""
    return [b % vocab_size for b in prompt.encode("utf-8")]


def decode_token(token: int) -> str:
    """Inverse of encode_text for printable bytes; empty otherwise."""
    return chr(token) if 32 <= token < 127 else ""


@deployment(max_ongoing_requests=8)
class LLMDeployment:
    """Streaming LLM deployment. Bind with an EngineConfig (or dict of its
    fields): ``serve.run(LLMDeployment.bind(EngineConfig(...)))``.

    Multi-chip replicas: pass ``mesh=`` (a ``ModelParallelConfig``, a
    ``parallel.MeshSpec``, a built ``jax.sharding.Mesh``, or a dict of
    axis sizes) — or set ``tp``/``fsdp`` on the EngineConfig itself — and
    the replica's engine runs the tp/fsdp ShardedExecutor over that mesh
    (docs/SERVING_LLM.md "Sharded serving"). Defaults stay single-device;
    request payloads, streaming, failover, and the prefix cache are
    identical either way — a stream started on a sharded replica resumes
    byte-identically on a single-chip one and vice versa."""

    def __init__(
        self,
        engine_config: EngineConfig | dict | None = None,
        mesh: Any = None,
    ):
        if isinstance(engine_config, dict):
            engine_config = EngineConfig(**engine_config)
        if mesh is not None:
            import dataclasses

            engine_config = dataclasses.replace(
                engine_config or EngineConfig(), mesh=mesh
            )
        self.engine = LLMEngine(engine_config)
        # external request_id -> engine-internal id, for cancel()
        self._active: dict[str, Any] = {}
        # same mapping, kept (bounded) after completion for
        # request_timeline() lookups on finished streams
        self._recent: OrderedDict[str, Any] = OrderedDict()
        self._resumed_total = 0
        self._m_resumed = metrics.counter(
            "llm_requests_resumed",
            "Streams resumed on this replica after another replica died",
        )
        # graceful-drain latch (controller-driven scale-down): a draining
        # replica admits nothing new; in-flight streams finish or hand off
        self._draining = False

    def __call__(self, payload: dict | None):
        """Generator: one chunk per generated token.

        payload: {"prompt": str | [int], "max_new_tokens"?, "temperature"?,
        "top_k"?, "top_p"?, "seed"?, "request_id"?, "deadline_s"?,
        "prior_tokens"?}.
        Chunks: {"request_id": str, "token": id, "index": i, "text": str}
        where ``index`` is absolute — a resumed stream continues the
        numbering of the stream it replaces.
        """
        if self._draining:
            # Scale-down marked this replica draining; the routing table
            # already excludes it, so only a dispatch racing the table
            # refresh lands here. EngineOverloadedError is the retryable
            # "go elsewhere" signal: failover resumes re-dispatch to a
            # survivor, fresh requests get 503 + Retry-After.
            raise EngineOverloadedError(
                "replica is draining for scale-down; retry another replica"
            )
        payload = payload or {}
        prompt = payload.get("prompt", "")
        if isinstance(prompt, str):
            prompt = encode_text(prompt, self.engine.model_cfg.vocab_size)
        prompt = [int(t) for t in prompt]
        request_id = str(payload.get("request_id") or uuid.uuid4().hex)
        prior = [int(t) for t in payload.get("prior_tokens") or ()]
        max_new = int(payload.get("max_new_tokens", 16))
        if prior:
            self._resumed_total += 1
            self._m_resumed.inc()
            if len(prior) >= max_new:
                return  # the dead replica already delivered everything
        deadline_s = payload.get("deadline_s")
        sampling = SamplingParams(
            max_new_tokens=max_new - len(prior),
            temperature=float(payload.get("temperature", 0.0)),
            top_k=int(payload.get("top_k", 0)),
            top_p=float(payload.get("top_p", 1.0)),
            seed=int(payload.get("seed", 0)),
            deadline_s=float(deadline_s) if deadline_s is not None else None,
            start_index=len(prior),
        )
        # the replica method runs inside a task_span when the caller was
        # traced — hand that context to the engine so its phase spans join
        # the same trace, and stamp the trace id on every chunk so a
        # resumed stream can assert trace continuity across replicas
        trace_ctx = tracing.current_context()
        trace_id = trace_ctx["trace_id"] if trace_ctx else None
        stream = self.engine.submit(
            prompt + prior, sampling, trace_ctx=trace_ctx
        )
        self._active[request_id] = stream.request_id
        self._recent[request_id] = stream.request_id
        while len(self._recent) > _RECENT_REQUESTS:
            self._recent.popitem(last=False)
        try:
            for i, tok in enumerate(stream):
                index = len(prior) + i
                chunk = {
                    "request_id": request_id,
                    "token": int(tok),
                    "index": index,
                    "text": decode_token(tok),
                }
                if trace_id is not None:
                    chunk["trace_id"] = trace_id
                yield chunk
                chaos.fire(
                    "llm.token",
                    index=index,
                    resumed=bool(prior),
                    tag=payload.get("chaos_tag"),
                )
        finally:
            self._active.pop(request_id, None)

    def cancel(self, request_id: str) -> bool:
        """Evict ``request_id`` and free its KV blocks now. Idempotent and
        safe to broadcast: replicas not serving the stream return False."""
        internal = self._active.get(str(request_id))
        if internal is None:
            return False
        return self.engine.cancel(internal)

    def check_health(self) -> None:
        """Controller health-check hook: a failed engine (step raised or
        watchdog fired) reports unhealthy so the replica gets replaced."""
        if self.engine.failed:
            raise RuntimeError("llm engine failed; replica must be replaced")

    def stats(self) -> dict:
        """Engine introspection (unary method — callable via handle)."""
        out = self.engine.stats()
        out["requests_resumed"] = self._resumed_total
        return out

    def request_timeline(self, request_id: str) -> dict | None:
        """Phase timeline of one EXTERNAL request id — live or recently
        finished on this replica; None if this replica never served it
        (broadcast to find the owner, like cancel)."""
        internal = self._active.get(str(request_id))
        if internal is None:
            internal = self._recent.get(str(request_id))
        if internal is None:
            return None
        return self.engine.request_timeline(internal)

    def debug_dump(self) -> dict:
        """Flight-recorder ring + engine/cache stats (the payload behind
        the proxy's /debug/llm endpoint)."""
        out = self.engine.debug_dump()
        out["requests_resumed"] = self._resumed_total
        out["draining"] = self._draining
        return out

    # ---------------- autoscaling & graceful drain ----------------

    def autoscaling_snapshot(self) -> dict:
        """Engine saturation signals for the controller's autoscaler
        (docs/SERVING_LLM.md "Autoscaling & graceful drain"). The
        ``llm.snapshot`` chaos point sits here so the load harness can
        delay/jitter snapshot reporting deterministically."""
        chaos.fire("llm.snapshot")
        out = self.engine.autoscaling_snapshot()
        out["draining"] = self._draining
        out["active_streams"] = len(self._active)
        return out

    def prepare_drain(self) -> dict:
        """Controller scale-down hook: stop admitting, keep serving.

        After this returns, new ``__call__`` dispatches are refused with
        ``EngineOverloadedError`` while every in-flight stream keeps
        decoding; the controller polls ``drain_status`` and finishes (or
        kills — the failover path hands the streams to survivors
        byte-identically) once the replica is idle or the drain deadline
        expires. Idempotent."""
        self._draining = True
        chaos.fire("replica_drain", active=len(self._active))
        return self.drain_status()

    def drain_status(self) -> dict:
        return {
            "draining": self._draining,
            "active_streams": len(self._active),
        }

    def finish_drain(self) -> dict:
        """Terminal drain step, called by the controller once no streams
        are active: returns every KV block (allocations, reservations,
        quarantine, prefix cache) to the pool via the engine's
        ``release_all`` shutdown path and reports the final accounting so
        the caller can assert the pool is leak-free before the actor is
        killed."""
        self.engine.shutdown()
        snap = self.engine.cache.debug_snapshot()
        return {
            "released": True,
            "leaked_blocks": snap["used_blocks"],
            "cache": snap,
        }


def stream_tokens(handle, payload: dict, *, max_failovers: int = 2):
    """Stream token chunks from an LLMDeployment handle with automatic
    mid-stream failover: if the serving replica dies, re-submit to a
    survivor with ``prior_tokens`` set to everything already received.
    Deterministic sampling makes the joined stream byte-identical to an
    uninterrupted run. Returns an iterator of chunk dicts."""
    payload = dict(payload)
    payload.setdefault("request_id", uuid.uuid4().hex)

    def resume(chunks):
        resumed = dict(payload)
        resumed["prior_tokens"] = [c["token"] for c in chunks]
        return resumed

    return handle.stream_with_failover(
        payload, resume=resume, max_failovers=max_failovers
    )


def build_llm_app(
    engine_config: EngineConfig | dict | None = None,
    *,
    mesh: Any = None,
    tp: int = 1,
    fsdp: int = 1,
    speculative_k: int | None = None,
    drafter: Any = None,
    **deployment_options: Any,
) -> Application:
    """Convenience: ``serve.run(build_llm_app(EngineConfig(...)))``.
    ``deployment_options`` forward to ``.options(...)`` (num_replicas,
    ray_actor_options for TPU chips, ...).

    ``mesh``/``tp``/``fsdp`` select the per-replica model-parallel
    layout (they override the EngineConfig fields of the same names);
    the defaults keep every replica single-device. ``speculative_k`` /
    ``drafter`` likewise override the engine's speculative-decoding
    knobs (docs/SERVING_LLM.md "Speculative decoding") — committed
    streams stay byte-identical with speculation on or off, so mixed
    fleets (some replicas speculative, some not) fail over freely."""
    overrides: dict = {}
    if mesh is not None or tp != 1 or fsdp != 1:
        overrides.update(mesh=mesh, tp=tp, fsdp=fsdp)
    if speculative_k is not None:
        overrides["speculative_k"] = int(speculative_k)
    if drafter is not None:
        overrides["drafter"] = drafter
    if overrides:
        import dataclasses

        if isinstance(engine_config, dict):
            engine_config = EngineConfig(**engine_config)
        engine_config = dataclasses.replace(
            engine_config or EngineConfig(), **overrides
        )
    dep = LLMDeployment
    if deployment_options:
        dep = dep.options(**deployment_options)
    return dep.bind(engine_config)
