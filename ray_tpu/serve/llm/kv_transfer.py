"""Versioned KV-block wire format for disaggregated prefill/decode.

A prefill replica finishes chunked prefill, exports the request's full
prompt blocks from its paged pool, and packs them with this module into
ONE contiguous byte payload that is sealed into the shared-memory
object store (``ray_tpu.put`` path).  The decode replica fetches the
payload, verifies integrity, and lands the blocks into its own pool via
the fused scatter in ``ops.kv_cache.land_blocks``.

Wire layout (all integers little-endian):

    MAGIC   4 bytes   b"RTKV"
    VERSION u16       wire version (bump on any layout change)
    HLEN    u32       length of the JSON header that follows
    HEADER  HLEN      json: {n_layer, block_size, n_kv_head, head_dim,
                             dtype, num_blocks, prefix_tokens}
    then, per block, in chain order:
      CHAIN   16 bytes  blake2b-16 token-chain digest (PR-3 prefix
                        machinery) — lets the decode side verify the
                        block corresponds to ITS tokenization of the
                        prompt before adopting it
      CONTENT 16 bytes  blake2b-16 over the raw k||v payload bytes —
                        catches corruption/truncation in transit
      K       n_layer*block_size*n_kv_head*head_dim * itemsize bytes
      V       same size

Integrity is layered: the header pins the tensor layout (a mismatched
mesh/model simply refuses the handoff), the chain digest pins *which
tokens* each block encodes, and the content digest pins the bytes.  Any
mismatch raises :class:`KVTransferError` — callers treat that exactly
like a lost object and fall back to local prefill; a torn handoff must
never become a corrupted stream.

This module is deliberately device-free: it only ever touches numpy
arrays the executor has already synced host-side (``np.frombuffer`` /
``ndarray.tobytes`` — no ``np.asarray`` on device values), so the
serve/llm host-sync lint applies to it unchanged.
"""
from __future__ import annotations

import hashlib
import json
import struct
from dataclasses import dataclass

import numpy as np

from ray_tpu._private.ids import ObjectID

MAGIC = b"RTKV"
WIRE_VERSION = 1
_DIGEST = 16  # blake2b digest_size, matches kv_cache._block_key
_HDR = struct.Struct("<4sHI")


class KVTransferError(RuntimeError):
    """A KV handoff payload failed validation (layout / digest / size).

    Treated by the decode side exactly like a lost object: re-prefill
    locally rather than decode from suspect blocks.
    """


@dataclass(frozen=True)
class KVLayout:
    """Tensor layout a handoff payload was packed under.  Both sides
    must agree exactly — blocks from a different model/mesh shape are
    not landable."""

    n_layer: int
    block_size: int
    n_kv_head: int
    head_dim: int
    dtype: str

    @property
    def block_bytes(self) -> int:
        n = self.n_layer * self.block_size * self.n_kv_head * self.head_dim
        return n * np.dtype(_resolve_dtype(self.dtype)).itemsize


def _resolve_dtype(name: str):
    """Resolve a dtype name to something numpy can address, including
    the ML dtypes (bfloat16) jax registers via ml_dtypes."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # ships with jax

        return np.dtype(getattr(ml_dtypes, name))


def handoff_object_id(request_id: str, attempt: int) -> ObjectID:
    """Deterministic object id for one handoff attempt.

    Determinism is what makes the retry state machine idempotent: a
    re-driven seal for the same (request, attempt) writes the same id
    (put is idempotent on ST_EXISTS), and the client can leak-sweep
    every attempt id it ever derived without having heard back from a
    killed prefill replica.
    """
    h = hashlib.blake2b(
        f"kvxfer:{request_id}:{attempt}".encode(), digest_size=ObjectID.SIZE
    )
    return ObjectID(h.digest())


def pack_blocks(
    layout: KVLayout,
    records: list[tuple[bytes, np.ndarray, np.ndarray]],
    *,
    prefix_tokens: int,
) -> bytes:
    """Pack ``records`` — (chain_digest, k_block, v_block) in chain
    order — into one wire payload.  Each k/v block has shape
    [n_layer, block_size, n_kv_head, head_dim]."""
    header = {
        "n_layer": layout.n_layer,
        "block_size": layout.block_size,
        "n_kv_head": layout.n_kv_head,
        "head_dim": layout.head_dim,
        "dtype": layout.dtype,
        "num_blocks": len(records),
        "prefix_tokens": prefix_tokens,
    }
    hjson = json.dumps(header, sort_keys=True).encode()
    parts = [_HDR.pack(MAGIC, WIRE_VERSION, len(hjson)), hjson]
    for chain_digest, k_block, v_block in records:
        if len(chain_digest) != _DIGEST:
            raise KVTransferError(
                f"chain digest must be {_DIGEST} bytes, got "
                f"{len(chain_digest)}"
            )
        payload = k_block.tobytes() + v_block.tobytes()
        if len(payload) != 2 * layout.block_bytes:
            raise KVTransferError(
                f"block payload is {len(payload)} bytes, layout says "
                f"{2 * layout.block_bytes}"
            )
        content = hashlib.blake2b(payload, digest_size=_DIGEST).digest()
        parts.append(chain_digest)
        parts.append(content)
        parts.append(payload)
    return b"".join(parts)


def unpack_blocks(
    wire: bytes,
) -> tuple[KVLayout, int, list[tuple[bytes, np.ndarray, np.ndarray]]]:
    """Parse and verify a wire payload.

    Returns (layout, prefix_tokens, records) where records are
    (chain_digest, k_block, v_block) in chain order.  Raises
    :class:`KVTransferError` on any structural or digest mismatch —
    the caller falls back to local prefill.
    """
    if len(wire) < _HDR.size:
        raise KVTransferError("payload shorter than wire header")
    magic, version, hlen = _HDR.unpack_from(wire, 0)
    if magic != MAGIC:
        raise KVTransferError(f"bad magic {magic!r}")
    if version != WIRE_VERSION:
        raise KVTransferError(
            f"wire version {version} != supported {WIRE_VERSION}"
        )
    off = _HDR.size
    if len(wire) < off + hlen:
        raise KVTransferError("truncated header")
    try:
        header = json.loads(wire[off:off + hlen])
    except ValueError as e:
        raise KVTransferError(f"undecodable header: {e}") from e
    off += hlen
    try:
        layout = KVLayout(
            n_layer=int(header["n_layer"]),
            block_size=int(header["block_size"]),
            n_kv_head=int(header["n_kv_head"]),
            head_dim=int(header["head_dim"]),
            dtype=str(header["dtype"]),
        )
        num_blocks = int(header["num_blocks"])
        prefix_tokens = int(header["prefix_tokens"])
    except (KeyError, ValueError) as e:
        raise KVTransferError(f"malformed header: {e}") from e
    block_bytes = layout.block_bytes
    rec_size = 2 * _DIGEST + 2 * block_bytes
    if len(wire) != off + num_blocks * rec_size:
        raise KVTransferError(
            f"payload size {len(wire)} != expected "
            f"{off + num_blocks * rec_size} for {num_blocks} blocks"
        )
    dtype = _resolve_dtype(layout.dtype)
    shape = (layout.n_layer, layout.block_size, layout.n_kv_head,
             layout.head_dim)
    records: list[tuple[bytes, np.ndarray, np.ndarray]] = []
    for i in range(num_blocks):
        chain = wire[off:off + _DIGEST]
        off += _DIGEST
        content = wire[off:off + _DIGEST]
        off += _DIGEST
        payload = wire[off:off + 2 * block_bytes]
        off += 2 * block_bytes
        got = hashlib.blake2b(payload, digest_size=_DIGEST).digest()
        if got != content:
            raise KVTransferError(f"content digest mismatch on block {i}")
        k = np.frombuffer(payload[:block_bytes], dtype=dtype).reshape(shape)
        v = np.frombuffer(payload[block_bytes:], dtype=dtype).reshape(shape)
        records.append((chain, k, v))
    return layout, prefix_tokens, records
