"""Versioned KV-block wire format for disaggregated prefill/decode.

A prefill replica finishes chunked prefill, exports the request's full
prompt blocks from its paged pool, and packs them with this module into
ONE contiguous byte payload that is sealed into the shared-memory
object store (``ray_tpu.put`` path).  The decode replica fetches the
payload, verifies integrity, and lands the blocks into its own pool via
the fused scatter in ``ops.kv_cache.land_blocks``.

Wire layout (all integers little-endian):

    MAGIC   4 bytes   b"RTKV"
    VERSION u16       wire version (bump on any layout change)
    HLEN    u32       length of the JSON header that follows
    HEADER  HLEN      json: {n_layer, block_size, n_kv_head, head_dim,
                             dtype, num_blocks, prefix_tokens}
                      v2 (quantized pools) adds: {quantization,
                             scale_dtype} — dtype is then the QUANTIZED
                             storage dtype (int8 / float8_e4m3fn)
    then, per block, in chain order:
      CHAIN   16 bytes  blake2b-16 token-chain digest (PR-3 prefix
                        machinery) — lets the decode side verify the
                        block corresponds to ITS tokenization of the
                        prompt before adopting it
      CONTENT 16 bytes  blake2b-16 over the raw payload bytes below —
                        catches corruption/truncation in transit
      v1:  K || V       each n_layer*block_size*n_kv_head*head_dim
                        * itemsize bytes
      v2:  K || KS || V || VS   quantized data planes plus their
                        [n_layer, block_size, n_kv_head] scale planes

f32 pools keep emitting byte-for-byte v1 payloads; quantized pools emit
v2 (a 2-4x smaller record — the scale plane is 1/head_dim the size of
the f32 savings). ``unpack_blocks`` reads both, and an ``expect=``
layout turns any config mismatch (dtype, quantization kind, geometry)
into a loud, named :class:`KVTransferError` instead of the opaque
digest failure a silent reinterpret would produce downstream.

Integrity is layered: the header pins the tensor layout (a mismatched
mesh/model simply refuses the handoff), the chain digest pins *which
tokens* each block encodes, and the content digest pins the bytes.  Any
mismatch raises :class:`KVTransferError` — callers treat that exactly
like a lost object and fall back to local prefill; a torn handoff must
never become a corrupted stream.

This module is deliberately device-free: it only ever touches numpy
arrays the executor has already synced host-side (``np.frombuffer`` /
``ndarray.tobytes`` — no ``np.asarray`` on device values), so the
serve/llm host-sync lint applies to it unchanged.
"""
from __future__ import annotations

import hashlib
import json
import struct
from dataclasses import dataclass

import numpy as np

from ray_tpu._private.ids import ObjectID

MAGIC = b"RTKV"
WIRE_VERSION = 1        # f32 pools
WIRE_VERSION_QUANT = 2  # quantized pools (adds scale planes + header fields)
_DIGEST = 16  # blake2b digest_size, matches kv_cache._block_key
_HDR = struct.Struct("<4sHI")


class KVTransferError(RuntimeError):
    """A KV handoff payload failed validation (layout / digest / size).

    Treated by the decode side exactly like a lost object: re-prefill
    locally rather than decode from suspect blocks.
    """


@dataclass(frozen=True)
class KVLayout:
    """Tensor layout a handoff payload was packed under.  Both sides
    must agree exactly — blocks from a different model/mesh shape are
    not landable."""

    n_layer: int
    block_size: int
    n_kv_head: int
    head_dim: int
    dtype: str
    # quantized pools: the kind ("int8" | "fp8") and the scale plane's
    # dtype. None => v1 f32/bf16 payloads, byte-identical to pre-v2 wire.
    quantization: str | None = None
    scale_dtype: str = "float32"

    @property
    def block_bytes(self) -> int:
        """One side's DATA bytes per block (in ``dtype`` — the quantized
        storage dtype for v2 layouts)."""
        n = self.n_layer * self.block_size * self.n_kv_head * self.head_dim
        return n * np.dtype(_resolve_dtype(self.dtype)).itemsize

    @property
    def scale_bytes(self) -> int:
        """One side's scale-plane bytes per block (0 for f32 pools)."""
        if self.quantization is None:
            return 0
        n = self.n_layer * self.block_size * self.n_kv_head
        return n * np.dtype(_resolve_dtype(self.scale_dtype)).itemsize

    @property
    def record_payload_bytes(self) -> int:
        """K + V (+ scale planes) bytes per block record on the wire."""
        return 2 * (self.block_bytes + self.scale_bytes)


def _resolve_dtype(name: str):
    """Resolve a dtype name to something numpy can address, including
    the ML dtypes (bfloat16) jax registers via ml_dtypes."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # ships with jax

        return np.dtype(getattr(ml_dtypes, name))


def handoff_object_id(request_id: str, attempt: int) -> ObjectID:
    """Deterministic object id for one handoff attempt.

    Determinism is what makes the retry state machine idempotent: a
    re-driven seal for the same (request, attempt) writes the same id
    (put is idempotent on ST_EXISTS), and the client can leak-sweep
    every attempt id it ever derived without having heard back from a
    killed prefill replica.
    """
    h = hashlib.blake2b(
        f"kvxfer:{request_id}:{attempt}".encode(), digest_size=ObjectID.SIZE
    )
    return ObjectID(h.digest())


def _record_payload(layout: KVLayout, k_block, v_block) -> bytes:
    """Serialize one block record's payload per the layout's version:
    v1 is K||V; v2 (quantized) is K||KS||V||VS with the scale planes
    packed beside their data so landing is a verbatim scatter."""
    if layout.quantization is None:
        return k_block.tobytes() + v_block.tobytes()
    from ray_tpu.ops.quantization import QuantizedKV

    if not isinstance(k_block, QuantizedKV):
        raise KVTransferError(
            f"layout says quantization={layout.quantization!r} but the "
            f"block payload is a plain {type(k_block).__name__}"
        )
    return (
        k_block.data.tobytes() + k_block.scale.tobytes()
        + v_block.data.tobytes() + v_block.scale.tobytes()
    )


def pack_blocks(
    layout: KVLayout,
    records: list[tuple[bytes, np.ndarray, np.ndarray]],
    *,
    prefix_tokens: int,
) -> bytes:
    """Pack ``records`` — (chain_digest, k_block, v_block) in chain
    order — into one wire payload.  Each k/v block has shape
    [n_layer, block_size, n_kv_head, head_dim]; for a quantized layout
    the blocks are ``QuantizedKV`` records whose scale planes drop the
    trailing head_dim axis, and the wire is version 2."""
    header = {
        "n_layer": layout.n_layer,
        "block_size": layout.block_size,
        "n_kv_head": layout.n_kv_head,
        "head_dim": layout.head_dim,
        "dtype": layout.dtype,
        "num_blocks": len(records),
        "prefix_tokens": prefix_tokens,
    }
    version = WIRE_VERSION
    if layout.quantization is not None:
        version = WIRE_VERSION_QUANT
        header["quantization"] = layout.quantization
        header["scale_dtype"] = layout.scale_dtype
    hjson = json.dumps(header, sort_keys=True).encode()
    parts = [_HDR.pack(MAGIC, version, len(hjson)), hjson]
    for chain_digest, k_block, v_block in records:
        if len(chain_digest) != _DIGEST:
            raise KVTransferError(
                f"chain digest must be {_DIGEST} bytes, got "
                f"{len(chain_digest)}"
            )
        payload = _record_payload(layout, k_block, v_block)
        if len(payload) != layout.record_payload_bytes:
            raise KVTransferError(
                f"block payload is {len(payload)} bytes, layout says "
                f"{layout.record_payload_bytes}"
            )
        content = hashlib.blake2b(payload, digest_size=_DIGEST).digest()
        parts.append(chain_digest)
        parts.append(content)
        parts.append(payload)
    return b"".join(parts)


def _check_layout_match(layout: KVLayout, expect: KVLayout) -> None:
    """Raise a :class:`KVTransferError` NAMING every field on which a
    payload's layout disagrees with the pool that would land it. Without
    this, a dtype or quantization-kind mismatch reinterprets bytes and
    surfaces far away as an opaque digest/shape failure."""
    if layout == expect:
        return
    diffs = []
    for f in (
        "n_layer", "block_size", "n_kv_head", "head_dim", "dtype",
        "quantization", "scale_dtype",
    ):
        got, want = getattr(layout, f), getattr(expect, f)
        if got != want:
            diffs.append(f"{f}: payload={got!r} pool={want!r}")
    raise KVTransferError(
        "KV payload layout does not match this pool ("
        + "; ".join(diffs) + ")"
    )


def unpack_blocks(
    wire: bytes,
    *,
    expect: KVLayout | None = None,
) -> tuple[KVLayout, int, list[tuple[bytes, np.ndarray, np.ndarray]]]:
    """Parse and verify a wire payload (versions 1 and 2).

    Returns (layout, prefix_tokens, records) where records are
    (chain_digest, k_block, v_block) in chain order — plain arrays for
    v1, ``QuantizedKV`` (numpy leaves) for v2.  ``expect`` (the landing
    pool's layout) turns any config mismatch into a loud, field-naming
    error BEFORE bytes are reinterpreted.  Raises
    :class:`KVTransferError` on any structural or digest mismatch —
    the caller falls back to local prefill.
    """
    if len(wire) < _HDR.size:
        raise KVTransferError("payload shorter than wire header")
    magic, version, hlen = _HDR.unpack_from(wire, 0)
    if magic != MAGIC:
        raise KVTransferError(f"bad magic {magic!r}")
    if version not in (WIRE_VERSION, WIRE_VERSION_QUANT):
        raise KVTransferError(
            f"wire version {version} not in supported "
            f"{(WIRE_VERSION, WIRE_VERSION_QUANT)}"
        )
    off = _HDR.size
    if len(wire) < off + hlen:
        raise KVTransferError("truncated header")
    try:
        header = json.loads(wire[off:off + hlen])
    except ValueError as e:
        raise KVTransferError(f"undecodable header: {e}") from e
    off += hlen
    if version == WIRE_VERSION and "quantization" in header:
        raise KVTransferError("v1 payload carries quantization fields")
    if version == WIRE_VERSION_QUANT and "quantization" not in header:
        raise KVTransferError("v2 payload missing quantization fields")
    try:
        layout = KVLayout(
            n_layer=int(header["n_layer"]),
            block_size=int(header["block_size"]),
            n_kv_head=int(header["n_kv_head"]),
            head_dim=int(header["head_dim"]),
            dtype=str(header["dtype"]),
            quantization=(
                str(header["quantization"])
                if version == WIRE_VERSION_QUANT else None
            ),
            scale_dtype=(
                str(header.get("scale_dtype", "float32"))
                if version == WIRE_VERSION_QUANT else "float32"
            ),
        )
        num_blocks = int(header["num_blocks"])
        prefix_tokens = int(header["prefix_tokens"])
    except (KeyError, ValueError) as e:
        raise KVTransferError(f"malformed header: {e}") from e
    if expect is not None:
        _check_layout_match(layout, expect)
    block_bytes = layout.block_bytes
    scale_bytes = layout.scale_bytes
    rec_size = 2 * _DIGEST + layout.record_payload_bytes
    if len(wire) != off + num_blocks * rec_size:
        raise KVTransferError(
            f"payload size {len(wire)} != expected "
            f"{off + num_blocks * rec_size} for {num_blocks} blocks"
        )
    dtype = _resolve_dtype(layout.dtype)
    shape = (layout.n_layer, layout.block_size, layout.n_kv_head,
             layout.head_dim)
    records: list[tuple[bytes, np.ndarray, np.ndarray]] = []
    for i in range(num_blocks):
        chain = wire[off:off + _DIGEST]
        off += _DIGEST
        content = wire[off:off + _DIGEST]
        off += _DIGEST
        payload = wire[off:off + layout.record_payload_bytes]
        off += layout.record_payload_bytes
        got = hashlib.blake2b(payload, digest_size=_DIGEST).digest()
        if got != content:
            raise KVTransferError(f"content digest mismatch on block {i}")
        if layout.quantization is None:
            k = np.frombuffer(
                payload[:block_bytes], dtype=dtype
            ).reshape(shape)
            v = np.frombuffer(
                payload[block_bytes:], dtype=dtype
            ).reshape(shape)
        else:
            from ray_tpu.ops.quantization import QuantizedKV

            sdtype = _resolve_dtype(layout.scale_dtype)
            side = block_bytes + scale_bytes
            kb, vb = payload[:side], payload[side:]

            def _side(buf):
                data = np.frombuffer(
                    buf[:block_bytes], dtype=dtype
                ).reshape(shape)
                scale = np.frombuffer(
                    buf[block_bytes:], dtype=sdtype
                ).reshape(shape[:-1])
                return QuantizedKV(data, scale)

            k, v = _side(kb), _side(vb)
        records.append((chain, k, v))
    return layout, prefix_tokens, records
