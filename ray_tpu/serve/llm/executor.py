"""ModelExecutor — the seam between engine scheduling and model steps.

The engine (engine.py) is a host-side scheduler: admission, block tables,
bucketing, COW bookkeeping, timelines. Everything device-side — weights,
the paged KV pool arrays, the jitted prefill/decode calls, the single
token sync — lives behind the `ModelExecutor` interface in this module,
so "how many chips run the model" is an executor choice the scheduler
never sees. Two interchangeable implementations:

- `SingleDeviceExecutor` — exactly the PR 1-5 behavior: one chip, plain
  `jnp.asarray` staging, unsharded weights and KV pool. The default.
- `ShardedExecutor` — a tp/fsdp mesh over several chips (ROADMAP item 1:
  models larger than one chip's HBM). It builds a mesh from
  `ray_tpu.parallel.mesh.MeshSpec`, shards the weights with the same
  logical-axis rules training uses (parallel/sharding.py DEFAULT_RULES:
  heads/mlp/vocab -> tp, embed -> fsdp), and shards the paged KV pool
  along its HEAD axis over tp. Sharding propagates into the jitted steps
  GSPMD-style from the committed inputs — the process-shared jit
  wrappers in decode.py are reused as-is, so the compile-count contract
  ((prefill, prefill_chunk, decode) x bucket shapes) is frozen exactly
  as on one chip.

What stays host-side under sharding — deliberately: block tables, the
free list, prefix hashing, COW pair lists, and the quarantine are plain
Python/numpy state in kv_cache.py; only `cache.k` / `cache.v` are device
arrays, and only their placement changes. The engine's lag-1
dispatch-ahead pipeline, keyed (seed, position) sampling, and the single
O(batch) int32 `_host_tokens` sync point are executor-agnostic, so
failover resume stays byte-identical on any mesh shape — a stream begun
on a tp=2/fsdp=2 replica resumes bit-for-bit on a single-chip one.

The sanitizer lint (tests/test_sanitizers.py) enforces the sync-point
contract here exactly as it did in engine.py: `_host_tokens` below is
the ONE place in serve/llm allowed to materialize a device value.
"""
from __future__ import annotations

import logging
import math
from typing import Any

import numpy as np

from ray_tpu.serve.llm.decode import DecodeFns, family_param_axes

logger = logging.getLogger("ray_tpu.serve.llm")


def _host_tokens(tokens) -> np.ndarray:
    """The ONE device->host sync point on the emit path: materialize a
    step's sampled token ids as O(batch) int32 numpy — [B] for plain
    decode/prefill, [B, W+1] packed verdicts for a speculative verify
    step (still O(batch * k) int32, never logits). All other serve/llm
    code must stay on-device (tests/test_sanitizers.py lints this) —
    for every executor, sharded included."""
    return np.asarray(tokens, np.int32)


def _host_blocks(kv) -> np.ndarray:
    """The SECOND allowed device->host sync, off the emit path entirely:
    materialize a handful of finished KV blocks for a disaggregated
    prefill handoff (serve/llm/kv_transfer.py wire format). This runs
    once per handed-off request on the PREFILL replica — never inside
    the decode scheduler loop — and moves O(blocks) cache bytes, which
    is the whole point of the transfer. Allowlisted by name in
    tests/test_sanitizers.py next to ``_host_tokens``. Quantized pools
    export ``QuantizedKV`` slabs — data and scale planes cross together,
    still O(blocks) bytes (2-4x fewer of them)."""
    from ray_tpu.ops.quantization import QuantizedKV

    if isinstance(kv, QuantizedKV):
        return QuantizedKV(np.asarray(kv.data), np.asarray(kv.scale))
    return np.asarray(kv)


class ModelExecutor:
    """Device-side half of the LLM engine.

    The engine stages every input as numpy (its bucketed scratch pool)
    and calls one of the methods below; the executor owns placement:
    where the weights live, how the paged KV pool arrays (`cache.k` /
    `cache.v`) are laid out, and which devices the jitted step runs on.
    Shared base implementation = the single-device datapath; subclasses
    change placement in ``__init__``, never the call path — GSPMD infers
    the sharded programs from the committed inputs, which is what keeps
    the compile-signature set identical across executors.

    Interface consumed by engine.py:

    - ``prefill(tokens, lengths, tables, sample=)`` — monolithic
      whole-prompt prefill; returns on-device [B] sampled token ids and
      updates ``cache.k``/``cache.v`` in place.
    - ``prefill_chunk(tokens, lengths, starts, tables, sample=)`` — the
      chunked/prefix path at true positions.
    - ``decode_step(tokens, positions, tables, sample=)`` — one decode
      step; ``tokens`` is either a host staging array (cold dispatch) or
      the previous step's on-device array (the lag-1 steady feed).
    - ``verify_step(tokens, starts, draft_len, tables, sample=)`` — one
      speculative draft-and-verify step over a [B, W] window (column 0 =
      last committed token, then drafts); returns on-device packed
      [B, W+1] verdicts (ops/sampling.py ``verify_tokens``).
    - ``copy_blocks(pairs)`` — fused on-device COW block copies.
    - ``sync_tokens(tokens_dev)`` — THE O(batch) int32 host sync.
    - ``sync_verify(packed_dev)`` — the same sync point for a verify
      step's packed verdicts ([B, W+1] int32 through ``_host_tokens``).
    - ``on_new_signature`` — compile-event hook, forwarded to DecodeFns.
    """

    kind = "single"
    # set by build_executor from EngineConfig when speculation is on;
    # surfaced via describe() -> stats()/debug_dump()
    speculative: dict | None = None
    # ShardedExecutor defers weight quantization until after
    # shard_params (the axes tree must match the RAW param structure,
    # and quantizing committed sharded arrays lets GSPMD place the
    # scale shards next to their data).
    _defer_quantize = False

    def __init__(self, family: str, model_cfg, cache, *,
                 params: dict | None = None, seed: int = 0):
        import jax

        self.family = family
        self.model_cfg = model_cfg
        self.cache = cache
        self.fns = DecodeFns(family, model_cfg)
        self.params = (
            params
            if params is not None
            else self.fns.init(jax.random.PRNGKey(seed), model_cfg)
        )
        if not self._defer_quantize:
            self._maybe_quantize_params()

    def _maybe_quantize_params(self) -> None:
        """Quantize the serving weights per ``model_cfg.quantization``
        (ops/quantization.quantize_params over the family's quant-axes
        tree). Init always produces f32 masters — quantization is an
        executor-build step, so the training paths and the family init
        functions never see a QuantizedTensor. No-op when the knob is
        unset or the params are already quantized (pre-built params
        handed across replicas must not double-quantize)."""
        kind = getattr(self.model_cfg, "quantization", None)
        if kind is None:
            return
        import jax

        from ray_tpu.ops.quantization import QuantizedTensor, quantize_params
        from ray_tpu.serve.llm.decode import family_quant_axes

        already = any(
            isinstance(t, QuantizedTensor)
            for t in jax.tree.leaves(
                self.params,
                is_leaf=lambda t: isinstance(t, QuantizedTensor),
            )
        )
        if already:
            return
        self.params = quantize_params(
            self.params,
            family_quant_axes(self.family, self.model_cfg),
            kind,
        )

    # ---------------- compile-event hooks (DecodeFns pass-through) ----

    @property
    def on_new_signature(self):
        return self.fns.on_new_signature

    @on_new_signature.setter
    def on_new_signature(self, hook) -> None:
        self.fns.on_new_signature = hook

    @property
    def num_compiled_shapes(self) -> int:
        return self.fns.num_compiled_shapes

    @property
    def signatures(self) -> frozenset:
        return self.fns.signatures

    # ---------------- staging ----------------

    def _dev(self, x):
        """Host staging array -> device. On-device arrays (the lag-1
        token feed) pass through untouched. Uncommitted placement: jit
        moves the value to wherever the executable's sharding wants it,
        so the SAME code serves one chip and a mesh."""
        import jax.numpy as jnp

        return jnp.asarray(x)

    def _dev_sample(self, sample: dict | None):
        """Move the engine's ``sample=`` staging pytree on-device. The
        grammar allow-mask leaf rides here like every other control:
        ``[B, ceil(V/32)]`` uint32 for decode steps, ``[B, W, words]``
        for verify windows (one allow-set per column). Validated at the
        seam — a wrongly-typed mask would silently allow everything
        after the kernel's bit unpack — and shared by both
        SingleDeviceExecutor and ShardedExecutor (mask is replicated
        data; the sampler applies it after the logits all-reduce)."""
        if sample is None:
            return None
        mask = sample.get("mask")
        if mask is not None:
            assert mask.dtype == np.uint32 and mask.ndim in (2, 3), (
                "grammar allow-mask must be packed uint32 [B, words] or "
                f"[B, W, words], got {mask.dtype}/{mask.shape}"
            )
        return {k: self._dev(v) for k, v in sample.items()}

    # ---------------- the step interface ----------------

    def prefill(self, tokens, lengths, tables, sample=None):
        toks, self.cache.k, self.cache.v = self.fns.prefill(
            self.params, self.cache.k, self.cache.v,
            self._dev(tokens), self._dev(lengths), self._dev(tables),
            sample=self._dev_sample(sample),
        )
        return toks

    def prefill_chunk(self, tokens, lengths, starts, tables, sample=None):
        toks, self.cache.k, self.cache.v = self.fns.prefill(
            self.params, self.cache.k, self.cache.v,
            self._dev(tokens), self._dev(lengths), self._dev(tables),
            start=self._dev(starts), sample=self._dev_sample(sample),
        )
        return toks

    def decode_step(self, tokens, positions, tables, sample=None):
        toks, self.cache.k, self.cache.v = self.fns.decode(
            self.params, self.cache.k, self.cache.v,
            self._dev(tokens), self._dev(positions), self._dev(tables),
            sample=self._dev_sample(sample),
        )
        return toks

    def verify_step(self, tokens, starts, draft_len, tables, sample=None):
        out, self.cache.k, self.cache.v = self.fns.verify(
            self.params, self.cache.k, self.cache.v,
            self._dev(tokens), self._dev(starts), self._dev(draft_len),
            self._dev(tables), sample=self._dev_sample(sample),
        )
        return out

    def copy_blocks(self, pairs: list[tuple[int, int]]) -> None:
        """Clone shared KV blocks on device (COW) before a write lands.
        The (src, dst) list pads to a pow2 bucket with (0, 0) — copying
        the garbage block onto itself — so the jitted shape set stays
        closed. Runs sharded for free: the pool arrays carry their mesh
        sharding and block indices are head-axis-invariant."""
        if not pairs:
            return
        from ray_tpu.ops.kv_cache import copy_blocks

        width = 1 << (len(pairs) - 1).bit_length()
        src = np.zeros((width,), np.int32)
        dst = np.zeros((width,), np.int32)
        for i, (s, d) in enumerate(pairs):
            src[i] = s
            dst[i] = d
        self.cache.k, self.cache.v = copy_blocks(
            self.cache.k, self.cache.v, self._dev(src), self._dev(dst)
        )

    def export_blocks(
        self, block_ids: list[int]
    ) -> tuple[np.ndarray, np.ndarray]:
        """Materialize the given physical blocks host-side for a
        disaggregated handoff, or as the host-tier demote capture
        (``PagedKVCache.demote_fn`` — the engine installs this method, so
        spill traffic flows through the same allowlisted ``_host_blocks``
        funnel instead of growing a new device->host sync point): returns
        (k, v) each [n_layer, len(block_ids), block_size, H_kv, hd]
        numpy, in the given order. The gather pads to a pow2 bucket with
        block 0 so the traced shape set stays closed (same discipline as
        ``copy_blocks``); padding rows are sliced off host-side. On a
        mesh the gather output is unsharded along heads by the transfer
        itself — the wire format is mesh-agnostic, which is also what
        makes a host-tier entry demoted under tp=1 byte-identical to one
        demoted under tp=4."""
        if not block_ids:
            import jax

            def _empty(a):
                return np.zeros(
                    (a.shape[0], 0) + tuple(a.shape[2:]), a.dtype
                )

            return (
                jax.tree.map(_empty, self.cache.k),
                jax.tree.map(_empty, self.cache.v),
            )
        width = 1 << (len(block_ids) - 1).bit_length()
        ids = np.zeros((width,), np.int32)
        for i, b in enumerate(block_ids):
            ids[i] = b
        k = _host_blocks(self.cache.k[:, self._dev(ids)])
        v = _host_blocks(self.cache.v[:, self._dev(ids)])
        return k[:, : len(block_ids)], v[:, : len(block_ids)]

    def land_blocks(
        self, block_ids: list[int], k_new: np.ndarray, v_new: np.ndarray
    ) -> None:
        """Scatter externally-produced KV blocks (a fetched handoff
        payload, or a batch of host-tier promotions drained by
        ``engine._apply_promotions_locked``) into this executor's pool at
        ``block_ids``, all layers fused (ops/kv_cache.land_blocks). Pads
        the id list to a pow2 bucket targeting garbage block 0 with zero
        payload rows, so the jitted shape set stays closed — promotion
        traffic therefore adds no compile kinds; host->device staging is
        ONE batched transfer per call. On a mesh the committed inputs
        re-shard along kv heads automatically (same GSPMD inference as
        every other call), so both executors serve promotions through
        this one method."""
        if not block_ids:
            return
        import jax

        from ray_tpu.ops.kv_cache import land_blocks

        width = 1 << (len(block_ids) - 1).bit_length()
        ids = np.zeros((width,), np.int32)
        for i, b in enumerate(block_ids):
            ids[i] = b
        if width != len(block_ids):

            def _pad(a):
                pad = ((0, 0), (0, width - len(block_ids))) + tuple(
                    (0, 0) for _ in range(a.ndim - 2)
                )
                return np.pad(a, pad)

            k_new = jax.tree.map(_pad, k_new)
            v_new = jax.tree.map(_pad, v_new)
        self.cache.k, self.cache.v = land_blocks(
            self.cache.k, self.cache.v, self._dev(ids),
            jax.tree.map(self._dev, k_new), jax.tree.map(self._dev, v_new),
        )

    def sync_tokens(self, tokens_dev) -> np.ndarray:
        """THE device->host transfer: one step's sampled ids as [B] int32
        numpy. On a mesh the ids are replicated (every shard computes the
        full vocab argmax/pick after the logits all-reduce), so the
        transfer is the same O(batch) int32 regardless of device count."""
        toks = _host_tokens(tokens_dev)
        assert toks.dtype == np.int32 and toks.ndim == 1, (
            "sync path must move O(batch) int32, got "
            f"{toks.dtype}/{toks.shape}"
        )
        return toks

    def sync_verify(self, packed_dev) -> np.ndarray:
        """The SAME host sync point for a speculative verify step: one
        packed [B, W+1] int32 array (committed count + the window's
        target tokens) — O(batch * (k+2)) int32, still no logits and
        still exactly one transfer per step."""
        packed = _host_tokens(packed_dev)
        assert packed.dtype == np.int32 and packed.ndim == 2, (
            "verify sync path must move O(batch * k) int32, got "
            f"{packed.dtype}/{packed.shape}"
        )
        return packed

    # ---------------- introspection ----------------

    @property
    def num_params(self) -> int:
        """Parameter count of the weights THIS executor serves, summed
        from the params pytree's shape metadata (no device sync) — the
        analytic-FLOPs input for serving MFU (2*n_params FLOPs/token,
        forward-only; cf. the training side's 6*n_params in
        benchmarks/gpt_mfu.py and docs/ROOFLINE.md). QuantizedTensor
        leaves count their DATA elements only — the per-channel scale
        planes are bookkeeping, not model capacity — so MFU and the
        goodput gauges stay comparable between a quantized engine and
        its f32 twin."""
        import jax

        from ray_tpu.ops.quantization import QuantizedTensor

        if getattr(self, "_num_params", None) is None:
            leaves = jax.tree_util.tree_leaves(
                self.params,
                is_leaf=lambda t: isinstance(t, QuantizedTensor),
            )
            self._num_params = int(sum(
                t.data.size if isinstance(t, QuantizedTensor) else t.size
                for t in leaves
            ))
        return self._num_params

    @property
    def peak_tflops(self) -> float:
        """Aggregate peak bf16 TFLOP/s across this executor's devices —
        the MFU denominator. Reuses the per-chip table the training
        benchmarks publish against (benchmarks/gpt_mfu.py); on CPU the
        nominal 0.5 TFLOP/s keeps the ratio defined (not meaningful as a
        hardware ceiling, but nonzero and stable for CI)."""
        from ray_tpu.benchmarks.gpt_mfu import chip_peak_tflops

        if getattr(self, "_peak_tflops", None) is None:
            dev = self._devices()[0]
            self._peak_tflops = (
                chip_peak_tflops(dev) * float(self.num_devices)
            )
        return self._peak_tflops

    def _devices(self):
        import jax

        return jax.devices()

    @property
    def attention_backend(self) -> str:
        """The RESOLVED decode-attention backend the jitted model steps
        traced with ("xla" | "pallas") — the model config's knob with
        "auto" collapsed to the platform default."""
        from ray_tpu.ops.paged_attention import resolve_backend

        return resolve_backend(
            getattr(self.model_cfg, "attention_backend", "xla")
        )

    @property
    def num_devices(self) -> int:
        return 1

    def describe(self) -> dict:
        """Stable summary for stats()/debug_dump()/benchmarks: which
        executor is serving, over how many devices, and which decode
        attention backend the model steps compiled with."""
        return {"executor": self.kind, "devices": self.num_devices,
                "mesh": None,
                "attention_backend": self.attention_backend,
                "quantization": getattr(
                    self.model_cfg, "quantization", None),
                "speculative": self.speculative}


class SingleDeviceExecutor(ModelExecutor):
    """Exactly the single-chip engine of PRs 1-5: default-device weights
    and KV pool, including the lag-1 dispatch-ahead pipeline feed and
    fused sampling (both of which live in the shared call path above)."""

    kind = "single"


def _resolve_mesh(mesh, tp: int, fsdp: int):
    """Normalize the EngineConfig mesh surface to a jax Mesh.

    Accepts a built ``jax.sharding.Mesh``, a ``parallel.MeshSpec``, a
    ``serve.config.ModelParallelConfig`` (anything with tp/fsdp ints), a
    dict of MeshSpec axis sizes, or None + (tp, fsdp) ints. A spec with
    no wildcard may use FEWER devices than are visible — the mesh takes
    the first tp*fsdp — so differently-shaped replicas can coexist on
    one host (and in tests, on one virtual-device process)."""
    import jax
    from jax.sharding import Mesh

    from ray_tpu.parallel import MeshSpec, build_mesh

    if isinstance(mesh, Mesh):
        return mesh
    if mesh is None:
        spec = MeshSpec(tp=tp, fsdp=fsdp)
    elif isinstance(mesh, MeshSpec):
        spec = mesh
    elif isinstance(mesh, dict):
        spec = MeshSpec(**mesh)
    elif hasattr(mesh, "tp") and hasattr(mesh, "fsdp"):
        spec = MeshSpec(tp=int(mesh.tp), fsdp=int(mesh.fsdp))
    else:
        raise TypeError(
            "mesh must be a jax.sharding.Mesh, MeshSpec, "
            "ModelParallelConfig, dict of axis sizes, or None; got "
            f"{type(mesh).__name__}"
        )
    devices = jax.devices()
    sizes = spec.sizes()
    if all(v != -1 for v in sizes.values()):
        n = math.prod(sizes.values())
        if n > len(devices):
            raise ValueError(
                f"mesh {({k: v for k, v in sizes.items() if v > 1})} "
                f"needs {n} devices but only {len(devices)} are visible"
            )
        devices = devices[:n]
    return build_mesh(spec, devices)


class ShardedExecutor(ModelExecutor):
    """tp/fsdp execution over a device mesh.

    Placement (all decided here, in ``__init__``):

    - weights: `parallel.sharding.shard_params` with the family's
      logical-axis tree (models/{gpt,llama}.py ``*_param_axes``) under
      DEFAULT_RULES — heads/mlp/vocab shard over tp (Megatron), embed
      over fsdp (ZeRO-3); exactly the layout the training side proves.
    - paged KV pool: ``cache.k``/``cache.v``
      ([layer, block, slot, kv_head, head_dim]) shard along the KV-HEAD
      axis over tp and replicate over fsdp. Block granularity, tables,
      prefix hashes, COW and quarantine bookkeeping stay host-side in
      kv_cache.py, byte-for-byte the single-chip code.

    The step functions themselves are the process-shared jit wrappers
    from decode.py: sharding flows from the committed params/pool inputs
    (GSPMD), so no pjit re-wrap, no new compile kinds, and the engine's
    signature accounting is unchanged. Requires ``n_kv_head % tp == 0``
    (the pool's head axis must split evenly) and a tp/fsdp-only mesh —
    dp/sp/pp/ep serving is future roadmap, not silently wrong."""

    kind = "sharded"
    _defer_quantize = True  # quantize after shard_params (see base attr)

    def __init__(self, family: str, model_cfg, cache, *,
                 mesh=None, tp: int = 1, fsdp: int = 1,
                 params: dict | None = None, seed: int = 0):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from ray_tpu.parallel import AxisNames
        from ray_tpu.parallel.sharding import ShardingRules, shard_params

        self.mesh = _resolve_mesh(mesh, tp, fsdp)
        for axis in (AxisNames.DATA, AxisNames.PIPE, AxisNames.SEQ,
                     AxisNames.EXPERT):
            if self.mesh.shape[axis] != 1:
                raise ValueError(
                    "the serving executor shards tp/fsdp only; mesh axis "
                    f"{axis!r} has size {self.mesh.shape[axis]} (batch is "
                    "scheduled host-side, not dp-sharded)"
                )
        tp_size = self.mesh.shape[AxisNames.TENSOR]
        n_kv = getattr(model_cfg, "n_kv_head", model_cfg.n_head)
        if n_kv % tp_size != 0:
            raise ValueError(
                f"tp={tp_size} cannot shard the paged KV pool: the pool "
                f"splits along its head axis and n_kv_head={n_kv} is not "
                f"divisible by tp (choose tp from the divisors of "
                f"{n_kv})"
            )
        super().__init__(family, model_cfg, cache, params=params, seed=seed)
        self.rules = ShardingRules()
        self.params = shard_params(
            self.params, family_param_axes(family, model_cfg),
            self.mesh, self.rules,
        )
        # Quantization runs AFTER shard_params: the axes tree matches the
        # raw param structure, and quantizing committed sharded arrays
        # lets GSPMD keep each scale shard colocated with its data shard
        # (the amax reduction is over an axis, so the result is the same
        # on any mesh).
        self._maybe_quantize_params()
        # The KV-head axis (axis 3) is the tp shard axis for the 5-d data
        # plane AND the 4-d scale plane of a quantized pool — one spec
        # serves both leaves.
        kv_spec = PartitionSpec(None, None, None, AxisNames.TENSOR)
        sh = NamedSharding(self.mesh, kv_spec)
        cache.k = jax.tree.map(lambda a: jax.device_put(a, sh), cache.k)
        cache.v = jax.tree.map(lambda a: jax.device_put(a, sh), cache.v)

    @property
    def num_devices(self) -> int:
        return self.mesh.devices.size

    def _devices(self):
        return list(self.mesh.devices.flat)

    def describe(self) -> dict:
        return {
            "executor": self.kind,
            "devices": self.num_devices,
            # only the non-trivial axes — {"tp": 2, "fsdp": 2} reads as
            # the operator-facing mesh shape
            "mesh": {a: int(s) for a, s in self.mesh.shape.items()
                     if int(s) > 1},
            "attention_backend": self.attention_backend,
            "quantization": getattr(self.model_cfg, "quantization", None),
            "speculative": self.speculative,
        }


def build_executor(cfg, model_cfg, cache, *, params=None) -> ModelExecutor:
    """EngineConfig -> executor. Single-device unless the config names a
    mesh (``mesh=``) or widens an axis (``tp``/``fsdp`` > 1) — the
    default path constructs byte-for-byte the pre-seam engine."""
    if cfg.mesh is None and cfg.tp == 1 and cfg.fsdp == 1:
        ex = SingleDeviceExecutor(
            cfg.model, model_cfg, cache, params=params, seed=cfg.seed
        )
    else:
        ex = ShardedExecutor(
            cfg.model, model_cfg, cache, mesh=cfg.mesh, tp=cfg.tp,
            fsdp=cfg.fsdp, params=params, seed=cfg.seed,
        )
    k = int(getattr(cfg, "speculative_k", 0) or 0)
    if k > 0:
        drafter = getattr(cfg, "drafter", None)
        ex.speculative = {
            "speculative_k": k,
            "drafter": (drafter if isinstance(drafter, str)
                        else type(drafter).__name__ if drafter is not None
                        else None),
        }
    return ex
