"""Paged KV-cache manager: preallocated block pool + per-sequence tables.

vLLM-style paging (PAPERS.md: serving Gemma on Cloud TPU uses the same
structure): the cache is ONE preallocated array pair per model —

    k, v: [n_layer, num_blocks, block_size, n_kv_head, head_dim]

— and sequences own logical-position-ordered lists of physical block ids.
Fragmentation-free growth (append one block at a time), O(1) free, and
blocks returned on sequence completion are immediately reusable, so the
steady-state footprint is set by CONCURRENT tokens, not total traffic.

Block 0 is reserved as the garbage sink: padding rows and masked writes
are redirected there (ops/kv_cache.py), which keeps every jitted scatter
shape-static. The allocator therefore hands out blocks [1, num_blocks).

Admission control is reservation-based: the engine reserves a sequence's
WORST-CASE block count (prompt + max_new_tokens) before prefill, so a
running sequence can never fail a mid-flight append — the simple analog of
vLLM's preemption machinery, traded for a little capacity headroom
(docs/SERVING_LLM.md discusses the trade).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np


@dataclass(frozen=True)
class KVCacheConfig:
    n_layer: int
    n_kv_head: int
    head_dim: int
    num_blocks: int = 64
    block_size: int = 16
    dtype: Any = None  # jnp dtype; None -> bfloat16

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1  # block 0 is the garbage sink

    def blocks_for(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)  # ceil


@dataclass
class CacheStats:
    high_water_blocks: int = 0
    allocated_total: int = 0
    freed_total: int = 0
    tables: dict = field(default_factory=dict)


class PagedKVCache:
    """Host-side block accounting + the device cache arrays.

    Not thread-safe by itself — the engine serializes all access under its
    scheduler lock (one stepper at a time).
    """

    def __init__(self, cfg: KVCacheConfig):
        import jax.numpy as jnp

        self.cfg = cfg
        dtype = cfg.dtype if cfg.dtype is not None else jnp.bfloat16
        shape = (
            cfg.n_layer, cfg.num_blocks, cfg.block_size,
            cfg.n_kv_head, cfg.head_dim,
        )
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        # LIFO free list: a just-freed (cache-warm) block is reused first
        self._free: list[int] = list(range(1, cfg.num_blocks))
        self._tables: dict[Any, list[int]] = {}
        self._reserved = 0
        self.stats = CacheStats()

    # ---------------- reservation (admission control) ----------------

    def can_reserve(self, n_blocks: int) -> bool:
        return n_blocks <= len(self._free) - self._reserved

    def reserve(self, n_blocks: int) -> None:
        if not self.can_reserve(n_blocks):
            raise RuntimeError(
                f"cannot reserve {n_blocks} blocks: "
                f"{len(self._free)} free, {self._reserved} already reserved"
            )
        self._reserved += n_blocks

    def release_reservation(self, n_blocks: int) -> None:
        self._reserved -= n_blocks
        assert self._reserved >= 0, "reservation accounting went negative"

    # ---------------- allocate / append / free ----------------

    def allocate(self, seq_id) -> None:
        """Register a sequence with an empty block table."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        self._tables[seq_id] = []

    def ensure_capacity(self, seq_id, num_tokens: int, *, reserved=True):
        """Append blocks until the sequence can hold ``num_tokens``.
        Draws from this sequence's reservation when ``reserved``."""
        table = self._tables[seq_id]
        while len(table) * self.cfg.block_size < num_tokens:
            if not self._free:
                raise RuntimeError(
                    "KV block pool exhausted — reservation accounting bug"
                )
            table.append(self._free.pop())
            if reserved:
                self._reserved -= 1
            self.stats.allocated_total += 1
        self.stats.high_water_blocks = max(
            self.stats.high_water_blocks, self.used_blocks
        )

    def free(self, seq_id) -> int:
        """Return a finished sequence's blocks to the pool; -> count."""
        table = self._tables.pop(seq_id)
        self._free.extend(reversed(table))  # LIFO: newest block reused first
        self.stats.freed_total += len(table)
        return len(table)

    def release_all(self) -> int:
        """Free every sequence and drop all reservations (engine failure /
        shutdown path); -> blocks returned. Afterwards the free list is
        full again, so repeated engine create/shutdown cannot leak."""
        returned = 0
        for seq_id in list(self._tables):
            returned += self.free(seq_id)
        self._reserved = 0
        return returned

    # ---------------- views ----------------

    @property
    def used_blocks(self) -> int:
        return self.cfg.usable_blocks - len(self._free)

    @property
    def utilization(self) -> float:
        return self.used_blocks / max(1, self.cfg.usable_blocks)

    def block_table(self, seq_id, pad_to: int) -> np.ndarray:
        """[pad_to] int32 table, unallocated tail padded with garbage
        block 0 (those positions are always masked)."""
        table = self._tables[seq_id]
        if len(table) > pad_to:
            raise ValueError(
                f"sequence {seq_id!r} holds {len(table)} blocks, "
                f"table was asked to fit in {pad_to}"
            )
        out = np.zeros((pad_to,), np.int32)
        out[: len(table)] = table
        return out

    def num_allocated(self, seq_id) -> int:
        return len(self._tables[seq_id])
