"""Paged KV-cache manager: preallocated block pool + per-sequence tables
+ block-granular prefix cache (content-addressed blocks, COW, LRU evict).

vLLM-style paging (PAPERS.md: serving Gemma on Cloud TPU uses the same
structure): the cache is ONE preallocated array pair per model —

    k, v: [n_layer, num_blocks, block_size, n_kv_head, head_dim]

— and sequences own logical-position-ordered lists of physical block ids.
Fragmentation-free growth (append one block at a time), O(1) free, and
blocks returned on sequence completion are immediately reusable, so the
steady-state footprint is set by CONCURRENT tokens, not total traffic.

Block 0 is reserved as the garbage sink: padding rows and masked writes
are redirected there (ops/kv_cache.py), which keeps every jitted scatter
shape-static. The allocator therefore hands out blocks [1, num_blocks).

Admission control is reservation-based: the engine reserves a sequence's
WORST-CASE block count (prompt + max_new_tokens) before prefill, so a
running sequence can never fail a mid-flight append — the simple analog of
vLLM's preemption machinery, traded for a little capacity headroom
(docs/SERVING_LLM.md discusses the trade).

Prefix caching (the SGLang RadixAttention idea at block granularity):
every FULL prompt block is content-addressed by the chain hash of all
token ids up to and including it, so a new request whose prompt shares a
prefix with earlier traffic maps the shared blocks into its table instead
of recomputing their K/V. A block is then in one of three states:

  free        in ``_free``          — no meaningful content
  referenced  refcount >= 1         — mapped by one or more live tables
  cached      in ``_lru``           — refcount 0 but content-addressed;
                                      resurrectable by a future hit,
                                      evicted LRU when ``_free`` runs dry

Writes never land in a content-addressed or shared block: ``prepare_write``
redirects them copy-on-write onto a fresh private block (the device-side
clone is ``ops.kv_cache.copy_blocks``). Reservations draw uniformly from
hits, appends and COW copies, so the no-mid-flight-failure invariant is
unchanged; ``release_all`` also drops the content-addressed set, keeping
engine create/shutdown cycles leak-free.

Host-memory tier (``host_cache_bytes > 0``): LRU eviction DEMOTES a full
prefix block into a pinned host-side arena instead of discarding it —
the plasma spill model from the Ray object store, applied to KV. Each
arena entry is one RTKV v1 per-block record (kv_transfer.py): chain
digest + content digest + the raw k||v payload, so promotion re-verifies
bytes before they ever touch the device pool. ``peek_prefix`` /
``assign_prefix`` consult the arena after a device miss and PROMOTE hits
back: the block is claimed like an append (same reservation accounting)
and its payload is queued; the engine drains the queue as ONE fused
``land_blocks`` scatter per step through the executor seam — no new sync
points, no new compile kinds. This module stays device-free: the
device->host capture at demote time goes through ``demote_fn`` (the
engine installs ``executor.export_blocks``, the allowlisted
``_host_blocks`` funnel), and promotion payloads are plain numpy.
"""
from __future__ import annotations

import hashlib
import logging
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any

import numpy as np

logger = logging.getLogger("ray_tpu.serve.llm")


def _block_key(prev: bytes, block_tokens) -> bytes:
    """Chain hash for one full block: digest of (parent digest, the
    block's token ids). Identifying a block by the chain rather than its
    own tokens makes equal-content blocks at different prompt offsets
    distinct — a hit therefore always means 'same tokens from position
    0', never a mid-prompt coincidence."""
    h = hashlib.blake2b(prev, digest_size=16)
    h.update(np.asarray(block_tokens, np.int64).tobytes())
    return h.digest()


@dataclass(frozen=True)
class KVCacheConfig:
    n_layer: int
    n_kv_head: int
    head_dim: int
    num_blocks: int = 64
    block_size: int = 16
    dtype: Any = None  # jnp dtype; None -> bfloat16
    # Host-memory cache tier capacity. 0 disables the tier: LRU eviction
    # discards content exactly as before. When > 0, evicted prefix blocks
    # demote into a host arena of at most this many bytes (RTKV wire
    # size, so header + digests count against the cap — and a quantized
    # pool's 2-4x smaller records buy proportionally more entries).
    host_cache_bytes: int = 0
    # "int8" | "fp8" | None: store the pool quantized with per-(token,
    # head) scale planes (ops/quantization.QuantizedKV). Static — set
    # once at engine build (EngineConfig.quantization); dtype is then
    # the scale/compute reference dtype and the pool data dtype comes
    # from the kind.
    quantization: str | None = None

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1  # block 0 is the garbage sink

    def blocks_for(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)  # ceil


@dataclass
class CacheStats:
    high_water_blocks: int = 0
    allocated_total: int = 0
    freed_total: int = 0
    prefix_hit_blocks: int = 0
    prefix_hit_tokens: int = 0
    prefix_evicted_blocks: int = 0
    cow_copies: int = 0
    adopted_blocks: int = 0  # handoff blocks landed from another replica
    demoted_blocks: int = 0      # device blocks spilled into the host tier
    promoted_blocks: int = 0     # host-tier hits claimed back into the pool
    host_evicted_blocks: int = 0  # arena entries dropped to fit the byte cap
    promotion_drops: int = 0     # queued promotions invalidated before landing
    demote_drops: int = 0        # demote captures that failed (content lost)
    host_corrupt_drops: int = 0  # arena entries failing RTKV verification
    tables: dict = field(default_factory=dict)


class HostKVTier:
    """Pinned host-memory arena for demoted prefix blocks.

    Pure container: an LRU ``OrderedDict`` keyed by chain digest whose
    values are RTKV v1 wire payloads (kv_transfer.pack_blocks with exactly
    one record), byte-capacity-capped. Packing on the way in and
    unpacking on the way out reuses the transfer module's content
    addressing verbatim, so a bit flipped while a block sat in host RAM
    fails the content digest at promote time instead of corrupting the
    device pool. No device access, no policy — PagedKVCache owns when to
    demote, promote and verify.
    """

    def __init__(self, capacity_bytes: int, layout) -> None:
        self.capacity_bytes = int(capacity_bytes)
        self.layout = layout  # kv_transfer.KVLayout of the device pool
        self._wire: OrderedDict[bytes, bytes] = OrderedDict()
        self._nbytes = 0

    def __contains__(self, digest: bytes) -> bool:
        return digest in self._wire

    @property
    def blocks(self) -> int:
        return len(self._wire)

    @property
    def nbytes(self) -> int:
        return self._nbytes

    def digests(self):
        """Resident chain digests, most-recently-used first."""
        return reversed(self._wire)

    def touch(self, digest: bytes) -> None:
        if digest in self._wire:
            self._wire.move_to_end(digest)

    def put(self, digest: bytes, k_block, v_block) -> tuple[bool, int]:
        """Store one demoted block; -> (stored, arena entries evicted to
        make room). A payload larger than the whole cap is refused; a
        digest already resident is refreshed, not re-packed."""
        from ray_tpu.serve.llm import kv_transfer

        if digest in self._wire:
            self._wire.move_to_end(digest)
            return True, 0
        wire = kv_transfer.pack_blocks(
            self.layout, [(digest, k_block, v_block)], prefix_tokens=0
        )
        if len(wire) > self.capacity_bytes:
            return False, 0
        evicted = 0
        while self._nbytes + len(wire) > self.capacity_bytes:
            _, old = self._wire.popitem(last=False)  # oldest first
            self._nbytes -= len(old)
            evicted += 1
        self._wire[digest] = wire
        self._nbytes += len(wire)
        return True, evicted

    def get(self, digest: bytes):
        """Unpack + verify one entry; -> (k_block, v_block) numpy arrays.
        Raises kv_transfer.KVTransferError on any corruption — the caller
        must treat that as a miss and ``discard`` the entry."""
        from ray_tpu.serve.llm import kv_transfer

        wire = self._wire[digest]
        # expect= turns a layout/quantization mismatch into a loud,
        # field-naming error instead of an opaque digest failure.
        _, _, records = kv_transfer.unpack_blocks(wire, expect=self.layout)
        chain, k_block, v_block = records[0]
        if chain != digest:
            raise kv_transfer.KVTransferError(
                "host-tier entry chain digest mismatch"
            )
        self._wire.move_to_end(digest)
        return k_block, v_block

    def discard(self, digest: bytes) -> None:
        wire = self._wire.pop(digest, None)
        if wire is not None:
            self._nbytes -= len(wire)

    def clear(self) -> None:
        self._wire.clear()
        self._nbytes = 0


class PagedKVCache:
    """Host-side block accounting + the device cache arrays.

    Not thread-safe by itself — the engine serializes all access under its
    scheduler lock (one stepper at a time).
    """

    def __init__(self, cfg: KVCacheConfig):
        import jax.numpy as jnp

        self.cfg = cfg
        dtype = cfg.dtype if cfg.dtype is not None else jnp.bfloat16
        shape = (
            cfg.n_layer, cfg.num_blocks, cfg.block_size,
            cfg.n_kv_head, cfg.head_dim,
        )
        if cfg.quantization is not None:
            from ray_tpu.ops.quantization import (
                QuantizedKV,
                quant_dtype,
                resolve_quantization,
            )

            kind = resolve_quantization(cfg.quantization)
            qdt = quant_dtype(kind)
            # data in the kind's storage dtype + per-(slot, head) f32
            # scale planes — write_kv quantizes at exactly this
            # granularity, so appends never re-quantize a block.
            self.k = QuantizedKV(
                jnp.zeros(shape, qdt), jnp.zeros(shape[:-1], jnp.float32)
            )
            self.v = QuantizedKV(
                jnp.zeros(shape, qdt), jnp.zeros(shape[:-1], jnp.float32)
            )
        else:
            self.k = jnp.zeros(shape, dtype)
            self.v = jnp.zeros(shape, dtype)
        # LIFO free list: a just-freed (cache-warm) block is reused first
        self._free: list[int] = list(range(1, cfg.num_blocks))
        # Lag-aware release (dispatch-ahead decode): blocks freed while a
        # device step is still in flight park here instead of the free
        # list, so they cannot be handed to a new allocation until the
        # engine's next token sync PROVES the in-flight step (and any
        # speculative write it carries) has executed. flush_quarantine()
        # moves them to the free list at that sync.
        self._quarantine: list[int] = []
        self._tables: dict[Any, list[int]] = {}
        self._reserved = 0
        # prefix cache state
        self._ref: dict[int, int] = {}            # block -> live references
        self._lru: OrderedDict[int, None] = OrderedDict()  # refcount-0 cached
        self._hash_to_block: dict[bytes, int] = {}
        self._block_hash: dict[int, bytes] = {}
        # seq -> (chain digest so far, number of blocks hashed into it)
        self._chain: dict[Any, tuple[bytes, int]] = {}
        # bumped whenever a sequence's table CONTENT changes (append / COW /
        # prefix mapping) — lets the engine cache host-side numpy tables
        self._versions: dict[Any, int] = {}
        # --- host tier (plasma-style spill of evicted prefix blocks) ---
        # The engine installs the device->host capture funnel after it
        # builds the executor (``cache.demote_fn = executor.export_blocks``);
        # until then — and whenever the tier is disabled — eviction
        # discards content exactly as before.
        self.demote_fn = None
        if cfg.host_cache_bytes > 0:
            from ray_tpu.serve.llm import kv_transfer

            self.host_tier = HostKVTier(
                cfg.host_cache_bytes,
                kv_transfer.KVLayout(
                    n_layer=cfg.n_layer,
                    block_size=cfg.block_size,
                    n_kv_head=cfg.n_kv_head,
                    head_dim=cfg.head_dim,
                    dtype=self.k.dtype.name,
                    quantization=cfg.quantization,
                ),
            )
        else:
            self.host_tier = None
        # Promotions staged by assign_prefix, drained by the engine as ONE
        # fused land_blocks scatter at the top of the next dispatch window:
        # (chain digest, block id, k payload, v payload).
        self._pending_promotions: list[tuple[bytes, int, Any, Any]] = []
        # Blocks claimed for promotion whose payload has NOT landed on
        # device yet. Such a block must never be demote-exported (the
        # device content is still garbage); its bytes are safe — the host
        # tier keeps the entry through promotion precisely so eviction
        # before landing loses nothing.
        self._unlanded: set[int] = set()
        self.stats = CacheStats()

    # ---------------- reservation (admission control) ----------------

    @property
    def available_blocks(self) -> int:
        """Blocks an admission may claim: truly free + evictable cached."""
        return len(self._free) + len(self._lru)

    @property
    def spare_blocks(self) -> int:
        """Claimable blocks beyond outstanding reservations — the most a
        handoff landing can adopt without live admissions immediately
        evicting the freshly-landed payloads back out of the pool."""
        return max(0, self.available_blocks - self._reserved)

    @property
    def reserved_blocks(self) -> int:
        """Outstanding admission reservations — the engine's preemption
        trigger and the autoscaling snapshot subtract these from
        ``available_blocks`` to get what a new admission can claim."""
        return self._reserved

    def can_reserve(self, n_blocks: int) -> bool:
        return n_blocks <= self.available_blocks - self._reserved

    def reserve(self, n_blocks: int) -> None:
        if not self.can_reserve(n_blocks):
            raise RuntimeError(
                f"cannot reserve {n_blocks} blocks: "
                f"{self.available_blocks} available "
                f"({len(self._lru)} cached), {self._reserved} already reserved"
            )
        self._reserved += n_blocks

    def release_reservation(self, n_blocks: int) -> None:
        self._reserved -= n_blocks
        assert self._reserved >= 0, "reservation accounting went negative"

    # ---------------- allocate / append / free ----------------

    def allocate(self, seq_id) -> None:
        """Register a sequence with an empty block table."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        self._tables[seq_id] = []
        self._chain[seq_id] = (b"", 0)
        self._versions[seq_id] = 0

    def _take_block(self, *, reserved: bool) -> int:
        """Claim one writable block: from the free list, else by evicting
        the LRU-oldest content-addressed block (its hash entry dies; with
        the host tier enabled its content demotes instead of dying)."""
        if self._free:
            b = self._free.pop()
        elif self._lru:
            b, _ = self._lru.popitem(last=False)  # oldest first
            h = self._block_hash.pop(b)
            del self._hash_to_block[h]
            self.stats.prefix_evicted_blocks += 1
            self._demote_evicted(h, b)
        else:
            raise RuntimeError(
                "KV block pool exhausted — reservation accounting bug"
            )
        if reserved:
            self._reserved -= 1
        self.stats.allocated_total += 1
        return b

    def ensure_capacity(self, seq_id, num_tokens: int, *, reserved=True) -> int:
        """Append blocks until the sequence can hold ``num_tokens``.
        Draws from this sequence's reservation when ``reserved``.
        Returns the number of blocks appended."""
        table = self._tables[seq_id]
        appended = 0
        while len(table) * self.cfg.block_size < num_tokens:
            b = self._take_block(reserved=reserved)
            self._ref[b] = 1
            table.append(b)
            appended += 1
        if appended:
            self._versions[seq_id] += 1
            self.stats.high_water_blocks = max(
                self.stats.high_water_blocks, self.used_blocks
            )
        return appended

    def _deref(self, b: int, *, quarantine: bool = False) -> None:
        self._ref[b] -= 1
        if self._ref[b] == 0:
            del self._ref[b]
            if b in self._block_hash:
                # content survives, resurrectable until evicted. Never
                # quarantined: hashed blocks are full PROMPT blocks and
                # speculative decode writes land past the prompt (COW'd
                # onto private blocks by prepare_write), so no in-flight
                # step can scribble on them.
                self._lru[b] = None  # appended at the MRU end
            elif quarantine:
                self._quarantine.append(b)
            else:
                self._free.append(b)

    def free(self, seq_id, *, quarantine: bool = False) -> int:
        """Drop a finished sequence's references; -> table length. Blocks
        it shared with live sequences stay put; sole-owned blocks return
        to the free list, except content-addressed ones, which park in the
        LRU set (still resurrectable by a future prefix hit).

        ``quarantine=True`` (the engine's dispatch-ahead path): sole-owned
        blocks park in the quarantine instead of the free list until
        ``flush_quarantine`` — see the field comment in ``__init__``."""
        table = self._tables.pop(seq_id)
        self._chain.pop(seq_id, None)
        self._versions.pop(seq_id, None)
        for b in reversed(table):  # LIFO: newest block reused first
            self._deref(b, quarantine=quarantine)
        self.stats.freed_total += len(table)
        return len(table)

    def flush_quarantine(self) -> int:
        """Return quarantined blocks to the free list; -> count. The
        engine calls this right after a token sync: completing the sync
        proves every previously-dispatched device step has executed, so
        blocks freed before those dispatches are safe to reuse."""
        n = len(self._quarantine)
        if n:
            self._free.extend(self._quarantine)
            self._quarantine.clear()
        return n

    def release_all(self) -> int:
        """Free every sequence, drop all reservations AND the whole prefix
        cache (engine failure / shutdown path); -> blocks returned.
        Afterwards the free list is full again, so repeated engine
        create/shutdown cannot leak."""
        returned = 0
        for seq_id in list(self._tables):
            returned += self.free(seq_id)
        self.flush_quarantine()
        self._free.extend(self._lru)
        self._lru.clear()
        self._hash_to_block.clear()
        self._block_hash.clear()
        self._reserved = 0
        # Host tier dies with the device cache: a queued promotion landing
        # after release could scribble on a re-allocated block, and a
        # shutdown that kept arena bytes would leak across engine
        # create/shutdown cycles.
        self._pending_promotions.clear()
        self._unlanded.clear()
        if self.host_tier is not None:
            self.host_tier.clear()
        return returned

    # ---------------- prefix cache ----------------

    def peek_prefix(self, tokens) -> int:
        """Number of LEADING full blocks of ``tokens`` currently servable
        without recompute — resident on device (referenced or cached) OR
        demoted into the host tier. A pure lookup, no state change. The
        engine uses it to size the reservation before committing; a host
        hit that later fails RTKV verification in ``assign_prefix`` just
        shortens the assigned prefix, which the over-sized reservation
        already covers."""
        digest = b""
        bs = self.cfg.block_size
        hits = 0
        for i in range(len(tokens) // bs):
            digest = _block_key(digest, tokens[i * bs:(i + 1) * bs])
            if digest not in self._hash_to_block and not (
                self.host_tier is not None and digest in self.host_tier
            ):
                break
            hits += 1
        return hits

    def export_chain(self, tokens) -> list[tuple[bytes, int]]:
        """(chain digest, physical block) for each LEADING full block of
        ``tokens`` currently resident — ``peek_prefix`` that also names
        the blocks. The prefill side of a disaggregated handoff walks
        this to know WHICH pool blocks to ship and under which chain
        digests; a partial walk (some blocks already evicted) is still a
        valid, shorter handoff."""
        digest = b""
        bs = self.cfg.block_size
        out: list[tuple[bytes, int]] = []
        for i in range(len(tokens) // bs):
            digest = _block_key(digest, tokens[i * bs:(i + 1) * bs])
            b = self._hash_to_block.get(digest)
            if b is None:
                break
            out.append((digest, b))
        return out

    def has_digest(self, digest: bytes) -> bool:
        """Whether a chain digest is resident (referenced or cached) —
        lets the handoff landing path tell 'already here, skip' apart
        from 'pool full, stop' when ``adopt_block`` returns None."""
        return digest in self._hash_to_block

    def adopt_block(self, digest: bytes) -> int | None:
        """Claim one block for a handoff landing and content-address it
        under ``digest`` as a CACHED (refcount-0, LRU) entry — after the
        caller scatters the fetched payload into the returned id, a
        plain ``assign_prefix`` scores a local prefix hit on it.

        Idempotent and best-effort by design (the handoff retry state
        machine re-drives): returns None without side effects when the
        digest is already resident (a concurrent identical prompt — or
        this same handoff, retried) or when the pool has no claimable
        block. Adoption moves a block free -> cached (or recycles a
        cached one), so ``available_blocks`` — and therefore admission
        accounting — is unchanged."""
        if digest in self._hash_to_block:
            return None
        if not self._free and not self._lru:
            return None
        b = self._take_block(reserved=False)
        self._hash_to_block[digest] = b
        self._block_hash[b] = digest
        self._lru[b] = None  # MRU end: just-landed blocks evict last
        self.stats.adopted_blocks += 1
        return b

    def assign_prefix(self, seq_id, tokens, max_blocks: int | None = None) -> int:
        """Map the longest resident prefix of ``tokens`` (full blocks
        only, at most ``max_blocks``) into ``seq_id``'s table, taking one
        reference per block. Each mapped block draws one unit from the
        reservation — identical accounting to an append, so the caller's
        worst-case reservation covers hits and computes uniformly.
        Returns the number of PROMPT TOKENS covered (hits * block_size).
        Must run right after ``allocate`` (empty table)."""
        table = self._tables[seq_id]
        assert not table, "assign_prefix requires an empty table"
        digest = b""
        bs = self.cfg.block_size
        limit = len(tokens) // bs
        if max_blocks is not None:
            limit = min(limit, max_blocks)
        hits = 0
        for i in range(limit):
            nxt = _block_key(digest, tokens[i * bs:(i + 1) * bs])
            b = self._hash_to_block.get(nxt)
            if b is not None:
                if b in self._lru:  # resurrect: cached -> referenced
                    del self._lru[b]
                    self._ref[b] = 1
                else:
                    self._ref[b] += 1
                self._reserved -= 1
            else:
                # Device miss — promote from the host tier. The block is
                # claimed exactly like an append (one reservation unit),
                # content-addressed immediately, and its payload staged
                # for the engine's next batched land_blocks scatter. The
                # arena keeps its entry: that provenance is what makes
                # the block safe to evict again before landing.
                payload = self._host_lookup(nxt)
                if payload is None:
                    break
                b = self._take_block(reserved=True)
                self._ref[b] = 1
                self._hash_to_block[nxt] = b
                self._block_hash[b] = nxt
                self._pending_promotions.append((nxt, b, payload[0], payload[1]))
                self._unlanded.add(b)
                self.stats.promoted_blocks += 1
            table.append(b)
            digest = nxt
            hits += 1
        if hits:
            self._chain[seq_id] = (digest, hits)
            self._versions[seq_id] += 1
            self.stats.prefix_hit_blocks += hits
            self.stats.prefix_hit_tokens += hits * bs
            self.stats.high_water_blocks = max(
                self.stats.high_water_blocks, self.used_blocks
            )
        return hits * bs

    def register_prefix(self, seq_id, tokens, upto_tokens: int) -> int:
        """Content-address ``seq_id``'s full prompt blocks whose tokens
        [0, upto_tokens) are now fully written (engine calls this after
        each prefill chunk). Blocks whose chain hash is already claimed
        (a concurrent identical prompt) stay private. -> newly registered
        block count."""
        digest, hashed = self._chain[seq_id]
        table = self._tables[seq_id]
        bs = self.cfg.block_size
        nfull = min(upto_tokens // bs, len(tokens) // bs, len(table))
        registered = 0
        while hashed < nfull:
            digest = _block_key(
                digest, tokens[hashed * bs:(hashed + 1) * bs]
            )
            b = table[hashed]
            if digest not in self._hash_to_block and b not in self._block_hash:
                self._hash_to_block[digest] = b
                self._block_hash[b] = digest
                registered += 1
            hashed += 1
        self._chain[seq_id] = (digest, hashed)
        return registered

    def prepare_write(self, seq_id, start_pos: int, end_pos: int,
                      *, reserved=True) -> list[tuple[int, int]]:
        """Make positions [start_pos, end_pos) of ``seq_id`` writable.
        Any already-allocated block in that range that is shared
        (refcount > 1) or content-addressed gets a fresh private block in
        the table; the returned (src, dst) pairs must be applied on device
        with ``ops.kv_cache.copy_blocks`` BEFORE the write lands. The
        shared source keeps its hash entry (and its other readers), so a
        sequence appending into a shared tail block diverges without
        corrupting the cached prefix."""
        if end_pos <= start_pos:
            return []
        table = self._tables[seq_id]
        bs = self.cfg.block_size
        lo = start_pos // bs
        hi = min(len(table) - 1, (end_pos - 1) // bs)
        pairs: list[tuple[int, int]] = []
        for idx in range(lo, hi + 1):
            b = table[idx]
            if self._ref.get(b, 0) > 1 or b in self._block_hash:
                dst = self._take_block(reserved=reserved)
                self._ref[dst] = 1
                table[idx] = dst
                self._deref(b)
                pairs.append((b, dst))
                self.stats.cow_copies += 1
        if pairs:
            self._versions[seq_id] += 1
            self.stats.high_water_blocks = max(
                self.stats.high_water_blocks, self.used_blocks
            )
        return pairs

    # ---------------- host tier (demote / promote) ----------------

    def _demote_evicted(self, digest: bytes, block: int) -> None:
        """Spill one LRU-evicted prefix block into the host tier (no-op
        with the tier disabled or no ``demote_fn`` installed). Best-effort
        by design: a failed capture loses a CACHE entry, never
        correctness, so failures are counted + logged, not raised. A
        block whose promotion payload has not landed yet is never
        exported — its device bytes are still garbage; the arena kept the
        real content through the promotion, so nothing is lost unless the
        arena has meanwhile evicted that entry too."""
        tier = self.host_tier
        if tier is None:
            return
        if block in self._unlanded:
            # the queued landing is now stale (its hash mapping just
            # died); the drain filter drops it by digest mismatch
            self._unlanded.discard(block)
            if digest in tier:
                tier.touch(digest)
            else:
                self.stats.demote_drops += 1
                logger.warning(
                    "unlanded promoted block %d evicted after its arena "
                    "entry %s was dropped — content lost",
                    block, digest.hex(),
                )
            return
        if digest in tier:
            tier.touch(digest)  # already backed: refresh recency, skip export
            return
        if self.demote_fn is None:
            return
        from ray_tpu._private import chaos

        try:
            chaos.fire("llm.kv.demote", block=block)
            k, v = self.demote_fn([block])
            stored, evicted = tier.put(digest, k[:, 0], v[:, 0])
            if stored:
                self.stats.demoted_blocks += 1
                self.stats.host_evicted_blocks += evicted
            else:
                self.stats.demote_drops += 1
                logger.warning(
                    "host tier refused demoted block %d (payload exceeds "
                    "host_cache_bytes=%d)", block, tier.capacity_bytes,
                )
        except Exception as exc:
            self.stats.demote_drops += 1
            logger.warning(
                "host-tier demotion of block %d failed: %r", block, exc
            )

    def demote_chain(self, tokens, upto_tokens: int,
                     trace_ctx: dict | None = None) -> int:
        """Proactively back the leading full blocks of ``tokens`` (first
        ``upto_tokens`` of them) into the host tier — the preemption
        pause path (engine._preempt_one_locked): the paused stream's
        chain must survive device LRU eviction while it is parked, so
        its resume re-prefills from cache instead of recomputing. One
        batched ``demote_fn`` export for all missing blocks (the same
        engine-installed indirection ``_demote_evicted`` uses — the
        cache never touches the device itself). Best-effort like every
        demote: a failed capture costs recompute on resume, never
        correctness, so failures are counted + logged, not raised.
        Returns the number of blocks newly captured.

        ``trace_ctx`` (the paused request's stored trace context) makes
        the demote visible on the request's trace as a ``kv.demote``
        span — only traced preemptions pay for the span record."""
        import time as _time

        t0 = _time.time() if trace_ctx else 0.0
        captured = self._demote_chain(tokens, upto_tokens)
        if trace_ctx:
            from ray_tpu.util import tracing

            tracing.record_span(
                "kv.demote", trace_id=trace_ctx["trace_id"],
                parent_span_id=trace_ctx.get("parent_span_id"),
                start=t0, end=_time.time(), kind="kv",
                attrs={"blocks": captured,
                       "upto_tokens": min(upto_tokens, len(tokens))},
            )
        return captured

    def _demote_chain(self, tokens, upto_tokens: int) -> int:
        tier = self.host_tier
        if tier is None or self.demote_fn is None:
            return 0
        bs = self.cfg.block_size
        digest = b""
        todo: list[tuple[bytes, int]] = []
        for i in range(min(upto_tokens, len(tokens)) // bs):
            digest = _block_key(digest, tokens[i * bs:(i + 1) * bs])
            b = self._hash_to_block.get(digest)
            if b is None:
                break  # not registered (or already evicted): chain ends
            if digest in tier:
                tier.touch(digest)  # already backed: refresh recency
                continue
            if b in self._unlanded:
                # device bytes are still garbage (promotion queued but
                # not landed); the arena already holds the real content
                continue
            todo.append((digest, b))
        if not todo:
            return 0
        from ray_tpu._private import chaos

        captured = 0
        try:
            chaos.fire("llm.kv.demote", blocks=len(todo))
            k, v = self.demote_fn([b for _, b in todo])
            for i, (d, b) in enumerate(todo):
                stored, evicted = tier.put(d, k[:, i], v[:, i])
                if stored:
                    captured += 1
                    self.stats.demoted_blocks += 1
                    self.stats.host_evicted_blocks += evicted
                else:
                    self.stats.demote_drops += 1
                    logger.warning(
                        "host tier refused preemption-demoted block %d "
                        "(payload exceeds host_cache_bytes=%d)",
                        b, tier.capacity_bytes,
                    )
        except Exception as exc:
            self.stats.demote_drops += len(todo) - captured
            logger.warning(
                "host-tier chain demotion of %d blocks failed: %r",
                len(todo), exc,
            )
        return captured

    def _host_lookup(self, digest: bytes):
        """Fetch + verify one host-tier entry; -> (k, v) numpy blocks or
        None. Verification failure (bit rot in host RAM, a truncated
        write) is a miss: the entry is dropped, counted and logged —
        corrupt bytes must never land in the device pool."""
        tier = self.host_tier
        if tier is None or digest not in tier:
            return None
        try:
            return tier.get(digest)
        except Exception as exc:
            tier.discard(digest)
            self.stats.host_corrupt_drops += 1
            logger.warning(
                "host-tier entry %s failed verification, dropped: %r",
                digest.hex(), exc,
            )
            return None

    def take_pending_promotions(self) -> list[tuple[int, Any, Any]]:
        """Drain staged host->device promotions for the engine to land as
        ONE fused ``land_blocks`` scatter; -> (block id, k, v) records.
        Exactly-once: each staged record is returned at most once, and a
        record whose block lost its content address before landing (its
        sequence was cancelled and a racing admission evicted the block)
        is dropped here — the arena still holds the bytes, so the drop
        costs a future re-promotion, not content. Callers MUST follow a
        successful scatter with ``promotions_landed``."""
        if not self._pending_promotions:
            return []
        staged, self._pending_promotions = self._pending_promotions, []
        out: list[tuple[int, Any, Any]] = []
        for digest, b, k_block, v_block in staged:
            if self._block_hash.get(b) != digest:
                self._unlanded.discard(b)
                self.stats.promotion_drops += 1
                logger.debug(
                    "promotion of block %d dropped: evicted before landing", b
                )
                continue
            out.append((b, k_block, v_block))
        return out

    def promotions_landed(self, block_ids) -> None:
        """Ack that the payloads for ``block_ids`` (returned by
        ``take_pending_promotions``) are on device — they become ordinary
        resident prefix blocks, eligible for demote-export again."""
        for b in block_ids:
            self._unlanded.discard(b)

    def prefix_digest_summary(self, limit: int = 32) -> list[str]:
        """Bounded routing-key summary for the fleet router: hex chain
        digests of prefix blocks this cache can serve without recompute —
        device-resident entries newest-registered first, then host-tier
        entries most-recently-used first. Piggybacked on the autoscaling
        snapshot, so router staleness is bounded by the controller's poll
        period."""
        out: list[str] = []
        seen: set[bytes] = set()
        for digest in reversed(self._hash_to_block):
            if len(out) >= limit:
                return out
            out.append(digest.hex())
            seen.add(digest)
        if self.host_tier is not None:
            for digest in self.host_tier.digests():
                if len(out) >= limit:
                    break
                if digest not in seen:
                    out.append(digest.hex())
        return out

    # ---------------- views ----------------

    @property
    def used_blocks(self) -> int:
        """Blocks referenced by live tables (cached-but-unreferenced
        blocks are reclaimable, so they don't count as used)."""
        return self.cfg.usable_blocks - len(self._free) - len(self._lru)

    @property
    def cached_blocks(self) -> int:
        """Content-addressed blocks with no live reference (the LRU set)."""
        return len(self._lru)

    @property
    def utilization(self) -> float:
        return self.used_blocks / max(1, self.cfg.usable_blocks)

    def block_table(self, seq_id, pad_to: int) -> np.ndarray:
        """[pad_to] int32 table, unallocated tail padded with garbage
        block 0 (those positions are always masked)."""
        table = self._tables[seq_id]
        if len(table) > pad_to:
            raise ValueError(
                f"sequence {seq_id!r} holds {len(table)} blocks, "
                f"table was asked to fit in {pad_to}"
            )
        out = np.zeros((pad_to,), np.int32)
        out[: len(table)] = table
        return out

    def table_version(self, seq_id) -> int:
        """Monotonic per-sequence counter, bumped on any table-content
        change — cache key for host-side materialized block tables."""
        return self._versions[seq_id]

    def debug_snapshot(self) -> dict:
        """JSON-safe accounting snapshot for the engine's flight-recorder
        / debug dumps — block-pool state plus the cumulative CacheStats
        counters, no device arrays."""
        s = self.stats
        return {
            "num_blocks": self.cfg.num_blocks,
            "block_size": self.cfg.block_size,
            "used_blocks": self.used_blocks,
            "free_blocks": len(self._free),
            "quarantined_blocks": len(self._quarantine),
            "cached_blocks": self.cached_blocks,
            "reserved_blocks": self._reserved,
            "live_sequences": len(self._tables),
            "utilization": round(self.utilization, 4),
            "high_water_blocks": s.high_water_blocks,
            "allocated_total": s.allocated_total,
            "freed_total": s.freed_total,
            "prefix_hit_blocks": s.prefix_hit_blocks,
            "prefix_hit_tokens": s.prefix_hit_tokens,
            "prefix_evicted_blocks": s.prefix_evicted_blocks,
            "cow_copies": s.cow_copies,
            "adopted_blocks": s.adopted_blocks,
            "host_blocks": 0 if self.host_tier is None else self.host_tier.blocks,
            "host_bytes": 0 if self.host_tier is None else self.host_tier.nbytes,
            "demotions": s.demoted_blocks,
            "promotions": s.promoted_blocks,
            "host_evicted_blocks": s.host_evicted_blocks,
            "promotion_drops": s.promotion_drops,
            "demote_drops": s.demote_drops,
            "host_corrupt_drops": s.host_corrupt_drops,
        }

    def num_allocated(self, seq_id) -> int:
        return len(self._tables[seq_id])
