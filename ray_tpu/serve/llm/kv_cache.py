"""Paged KV-cache manager: preallocated block pool + per-sequence tables
+ block-granular prefix cache (content-addressed blocks, COW, LRU evict).

vLLM-style paging (PAPERS.md: serving Gemma on Cloud TPU uses the same
structure): the cache is ONE preallocated array pair per model —

    k, v: [n_layer, num_blocks, block_size, n_kv_head, head_dim]

— and sequences own logical-position-ordered lists of physical block ids.
Fragmentation-free growth (append one block at a time), O(1) free, and
blocks returned on sequence completion are immediately reusable, so the
steady-state footprint is set by CONCURRENT tokens, not total traffic.

Block 0 is reserved as the garbage sink: padding rows and masked writes
are redirected there (ops/kv_cache.py), which keeps every jitted scatter
shape-static. The allocator therefore hands out blocks [1, num_blocks).

Admission control is reservation-based: the engine reserves a sequence's
WORST-CASE block count (prompt + max_new_tokens) before prefill, so a
running sequence can never fail a mid-flight append — the simple analog of
vLLM's preemption machinery, traded for a little capacity headroom
(docs/SERVING_LLM.md discusses the trade).

Prefix caching (the SGLang RadixAttention idea at block granularity):
every FULL prompt block is content-addressed by the chain hash of all
token ids up to and including it, so a new request whose prompt shares a
prefix with earlier traffic maps the shared blocks into its table instead
of recomputing their K/V. A block is then in one of three states:

  free        in ``_free``          — no meaningful content
  referenced  refcount >= 1         — mapped by one or more live tables
  cached      in ``_lru``           — refcount 0 but content-addressed;
                                      resurrectable by a future hit,
                                      evicted LRU when ``_free`` runs dry

Writes never land in a content-addressed or shared block: ``prepare_write``
redirects them copy-on-write onto a fresh private block (the device-side
clone is ``ops.kv_cache.copy_blocks``). Reservations draw uniformly from
hits, appends and COW copies, so the no-mid-flight-failure invariant is
unchanged; ``release_all`` also drops the content-addressed set, keeping
engine create/shutdown cycles leak-free.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any

import numpy as np


def _block_key(prev: bytes, block_tokens) -> bytes:
    """Chain hash for one full block: digest of (parent digest, the
    block's token ids). Identifying a block by the chain rather than its
    own tokens makes equal-content blocks at different prompt offsets
    distinct — a hit therefore always means 'same tokens from position
    0', never a mid-prompt coincidence."""
    h = hashlib.blake2b(prev, digest_size=16)
    h.update(np.asarray(block_tokens, np.int64).tobytes())
    return h.digest()


@dataclass(frozen=True)
class KVCacheConfig:
    n_layer: int
    n_kv_head: int
    head_dim: int
    num_blocks: int = 64
    block_size: int = 16
    dtype: Any = None  # jnp dtype; None -> bfloat16

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1  # block 0 is the garbage sink

    def blocks_for(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)  # ceil


@dataclass
class CacheStats:
    high_water_blocks: int = 0
    allocated_total: int = 0
    freed_total: int = 0
    prefix_hit_blocks: int = 0
    prefix_hit_tokens: int = 0
    prefix_evicted_blocks: int = 0
    cow_copies: int = 0
    adopted_blocks: int = 0  # handoff blocks landed from another replica
    tables: dict = field(default_factory=dict)


class PagedKVCache:
    """Host-side block accounting + the device cache arrays.

    Not thread-safe by itself — the engine serializes all access under its
    scheduler lock (one stepper at a time).
    """

    def __init__(self, cfg: KVCacheConfig):
        import jax.numpy as jnp

        self.cfg = cfg
        dtype = cfg.dtype if cfg.dtype is not None else jnp.bfloat16
        shape = (
            cfg.n_layer, cfg.num_blocks, cfg.block_size,
            cfg.n_kv_head, cfg.head_dim,
        )
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        # LIFO free list: a just-freed (cache-warm) block is reused first
        self._free: list[int] = list(range(1, cfg.num_blocks))
        # Lag-aware release (dispatch-ahead decode): blocks freed while a
        # device step is still in flight park here instead of the free
        # list, so they cannot be handed to a new allocation until the
        # engine's next token sync PROVES the in-flight step (and any
        # speculative write it carries) has executed. flush_quarantine()
        # moves them to the free list at that sync.
        self._quarantine: list[int] = []
        self._tables: dict[Any, list[int]] = {}
        self._reserved = 0
        # prefix cache state
        self._ref: dict[int, int] = {}            # block -> live references
        self._lru: OrderedDict[int, None] = OrderedDict()  # refcount-0 cached
        self._hash_to_block: dict[bytes, int] = {}
        self._block_hash: dict[int, bytes] = {}
        # seq -> (chain digest so far, number of blocks hashed into it)
        self._chain: dict[Any, tuple[bytes, int]] = {}
        # bumped whenever a sequence's table CONTENT changes (append / COW /
        # prefix mapping) — lets the engine cache host-side numpy tables
        self._versions: dict[Any, int] = {}
        self.stats = CacheStats()

    # ---------------- reservation (admission control) ----------------

    @property
    def available_blocks(self) -> int:
        """Blocks an admission may claim: truly free + evictable cached."""
        return len(self._free) + len(self._lru)

    @property
    def spare_blocks(self) -> int:
        """Claimable blocks beyond outstanding reservations — the most a
        handoff landing can adopt without live admissions immediately
        evicting the freshly-landed payloads back out of the pool."""
        return max(0, self.available_blocks - self._reserved)

    def can_reserve(self, n_blocks: int) -> bool:
        return n_blocks <= self.available_blocks - self._reserved

    def reserve(self, n_blocks: int) -> None:
        if not self.can_reserve(n_blocks):
            raise RuntimeError(
                f"cannot reserve {n_blocks} blocks: "
                f"{self.available_blocks} available "
                f"({len(self._lru)} cached), {self._reserved} already reserved"
            )
        self._reserved += n_blocks

    def release_reservation(self, n_blocks: int) -> None:
        self._reserved -= n_blocks
        assert self._reserved >= 0, "reservation accounting went negative"

    # ---------------- allocate / append / free ----------------

    def allocate(self, seq_id) -> None:
        """Register a sequence with an empty block table."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already allocated")
        self._tables[seq_id] = []
        self._chain[seq_id] = (b"", 0)
        self._versions[seq_id] = 0

    def _take_block(self, *, reserved: bool) -> int:
        """Claim one writable block: from the free list, else by evicting
        the LRU-oldest content-addressed block (its hash entry dies)."""
        if self._free:
            b = self._free.pop()
        elif self._lru:
            b, _ = self._lru.popitem(last=False)  # oldest first
            h = self._block_hash.pop(b)
            del self._hash_to_block[h]
            self.stats.prefix_evicted_blocks += 1
        else:
            raise RuntimeError(
                "KV block pool exhausted — reservation accounting bug"
            )
        if reserved:
            self._reserved -= 1
        self.stats.allocated_total += 1
        return b

    def ensure_capacity(self, seq_id, num_tokens: int, *, reserved=True) -> int:
        """Append blocks until the sequence can hold ``num_tokens``.
        Draws from this sequence's reservation when ``reserved``.
        Returns the number of blocks appended."""
        table = self._tables[seq_id]
        appended = 0
        while len(table) * self.cfg.block_size < num_tokens:
            b = self._take_block(reserved=reserved)
            self._ref[b] = 1
            table.append(b)
            appended += 1
        if appended:
            self._versions[seq_id] += 1
            self.stats.high_water_blocks = max(
                self.stats.high_water_blocks, self.used_blocks
            )
        return appended

    def _deref(self, b: int, *, quarantine: bool = False) -> None:
        self._ref[b] -= 1
        if self._ref[b] == 0:
            del self._ref[b]
            if b in self._block_hash:
                # content survives, resurrectable until evicted. Never
                # quarantined: hashed blocks are full PROMPT blocks and
                # speculative decode writes land past the prompt (COW'd
                # onto private blocks by prepare_write), so no in-flight
                # step can scribble on them.
                self._lru[b] = None  # appended at the MRU end
            elif quarantine:
                self._quarantine.append(b)
            else:
                self._free.append(b)

    def free(self, seq_id, *, quarantine: bool = False) -> int:
        """Drop a finished sequence's references; -> table length. Blocks
        it shared with live sequences stay put; sole-owned blocks return
        to the free list, except content-addressed ones, which park in the
        LRU set (still resurrectable by a future prefix hit).

        ``quarantine=True`` (the engine's dispatch-ahead path): sole-owned
        blocks park in the quarantine instead of the free list until
        ``flush_quarantine`` — see the field comment in ``__init__``."""
        table = self._tables.pop(seq_id)
        self._chain.pop(seq_id, None)
        self._versions.pop(seq_id, None)
        for b in reversed(table):  # LIFO: newest block reused first
            self._deref(b, quarantine=quarantine)
        self.stats.freed_total += len(table)
        return len(table)

    def flush_quarantine(self) -> int:
        """Return quarantined blocks to the free list; -> count. The
        engine calls this right after a token sync: completing the sync
        proves every previously-dispatched device step has executed, so
        blocks freed before those dispatches are safe to reuse."""
        n = len(self._quarantine)
        if n:
            self._free.extend(self._quarantine)
            self._quarantine.clear()
        return n

    def release_all(self) -> int:
        """Free every sequence, drop all reservations AND the whole prefix
        cache (engine failure / shutdown path); -> blocks returned.
        Afterwards the free list is full again, so repeated engine
        create/shutdown cannot leak."""
        returned = 0
        for seq_id in list(self._tables):
            returned += self.free(seq_id)
        self.flush_quarantine()
        self._free.extend(self._lru)
        self._lru.clear()
        self._hash_to_block.clear()
        self._block_hash.clear()
        self._reserved = 0
        return returned

    # ---------------- prefix cache ----------------

    def peek_prefix(self, tokens) -> int:
        """Number of LEADING full blocks of ``tokens`` currently resident
        (referenced or cached) — a pure lookup, no state change. The
        engine uses it to size the reservation before committing."""
        digest = b""
        bs = self.cfg.block_size
        hits = 0
        for i in range(len(tokens) // bs):
            digest = _block_key(digest, tokens[i * bs:(i + 1) * bs])
            if digest not in self._hash_to_block:
                break
            hits += 1
        return hits

    def export_chain(self, tokens) -> list[tuple[bytes, int]]:
        """(chain digest, physical block) for each LEADING full block of
        ``tokens`` currently resident — ``peek_prefix`` that also names
        the blocks. The prefill side of a disaggregated handoff walks
        this to know WHICH pool blocks to ship and under which chain
        digests; a partial walk (some blocks already evicted) is still a
        valid, shorter handoff."""
        digest = b""
        bs = self.cfg.block_size
        out: list[tuple[bytes, int]] = []
        for i in range(len(tokens) // bs):
            digest = _block_key(digest, tokens[i * bs:(i + 1) * bs])
            b = self._hash_to_block.get(digest)
            if b is None:
                break
            out.append((digest, b))
        return out

    def has_digest(self, digest: bytes) -> bool:
        """Whether a chain digest is resident (referenced or cached) —
        lets the handoff landing path tell 'already here, skip' apart
        from 'pool full, stop' when ``adopt_block`` returns None."""
        return digest in self._hash_to_block

    def adopt_block(self, digest: bytes) -> int | None:
        """Claim one block for a handoff landing and content-address it
        under ``digest`` as a CACHED (refcount-0, LRU) entry — after the
        caller scatters the fetched payload into the returned id, a
        plain ``assign_prefix`` scores a local prefix hit on it.

        Idempotent and best-effort by design (the handoff retry state
        machine re-drives): returns None without side effects when the
        digest is already resident (a concurrent identical prompt — or
        this same handoff, retried) or when the pool has no claimable
        block. Adoption moves a block free -> cached (or recycles a
        cached one), so ``available_blocks`` — and therefore admission
        accounting — is unchanged."""
        if digest in self._hash_to_block:
            return None
        if not self._free and not self._lru:
            return None
        b = self._take_block(reserved=False)
        self._hash_to_block[digest] = b
        self._block_hash[b] = digest
        self._lru[b] = None  # MRU end: just-landed blocks evict last
        self.stats.adopted_blocks += 1
        return b

    def assign_prefix(self, seq_id, tokens, max_blocks: int | None = None) -> int:
        """Map the longest resident prefix of ``tokens`` (full blocks
        only, at most ``max_blocks``) into ``seq_id``'s table, taking one
        reference per block. Each mapped block draws one unit from the
        reservation — identical accounting to an append, so the caller's
        worst-case reservation covers hits and computes uniformly.
        Returns the number of PROMPT TOKENS covered (hits * block_size).
        Must run right after ``allocate`` (empty table)."""
        table = self._tables[seq_id]
        assert not table, "assign_prefix requires an empty table"
        digest = b""
        bs = self.cfg.block_size
        limit = len(tokens) // bs
        if max_blocks is not None:
            limit = min(limit, max_blocks)
        hits = 0
        for i in range(limit):
            nxt = _block_key(digest, tokens[i * bs:(i + 1) * bs])
            b = self._hash_to_block.get(nxt)
            if b is None:
                break
            if b in self._lru:  # resurrect: cached -> referenced
                del self._lru[b]
                self._ref[b] = 1
            else:
                self._ref[b] += 1
            table.append(b)
            self._reserved -= 1
            digest = nxt
            hits += 1
        if hits:
            self._chain[seq_id] = (digest, hits)
            self._versions[seq_id] += 1
            self.stats.prefix_hit_blocks += hits
            self.stats.prefix_hit_tokens += hits * bs
            self.stats.high_water_blocks = max(
                self.stats.high_water_blocks, self.used_blocks
            )
        return hits * bs

    def register_prefix(self, seq_id, tokens, upto_tokens: int) -> int:
        """Content-address ``seq_id``'s full prompt blocks whose tokens
        [0, upto_tokens) are now fully written (engine calls this after
        each prefill chunk). Blocks whose chain hash is already claimed
        (a concurrent identical prompt) stay private. -> newly registered
        block count."""
        digest, hashed = self._chain[seq_id]
        table = self._tables[seq_id]
        bs = self.cfg.block_size
        nfull = min(upto_tokens // bs, len(tokens) // bs, len(table))
        registered = 0
        while hashed < nfull:
            digest = _block_key(
                digest, tokens[hashed * bs:(hashed + 1) * bs]
            )
            b = table[hashed]
            if digest not in self._hash_to_block and b not in self._block_hash:
                self._hash_to_block[digest] = b
                self._block_hash[b] = digest
                registered += 1
            hashed += 1
        self._chain[seq_id] = (digest, hashed)
        return registered

    def prepare_write(self, seq_id, start_pos: int, end_pos: int,
                      *, reserved=True) -> list[tuple[int, int]]:
        """Make positions [start_pos, end_pos) of ``seq_id`` writable.
        Any already-allocated block in that range that is shared
        (refcount > 1) or content-addressed gets a fresh private block in
        the table; the returned (src, dst) pairs must be applied on device
        with ``ops.kv_cache.copy_blocks`` BEFORE the write lands. The
        shared source keeps its hash entry (and its other readers), so a
        sequence appending into a shared tail block diverges without
        corrupting the cached prefix."""
        if end_pos <= start_pos:
            return []
        table = self._tables[seq_id]
        bs = self.cfg.block_size
        lo = start_pos // bs
        hi = min(len(table) - 1, (end_pos - 1) // bs)
        pairs: list[tuple[int, int]] = []
        for idx in range(lo, hi + 1):
            b = table[idx]
            if self._ref.get(b, 0) > 1 or b in self._block_hash:
                dst = self._take_block(reserved=reserved)
                self._ref[dst] = 1
                table[idx] = dst
                self._deref(b)
                pairs.append((b, dst))
                self.stats.cow_copies += 1
        if pairs:
            self._versions[seq_id] += 1
            self.stats.high_water_blocks = max(
                self.stats.high_water_blocks, self.used_blocks
            )
        return pairs

    # ---------------- views ----------------

    @property
    def used_blocks(self) -> int:
        """Blocks referenced by live tables (cached-but-unreferenced
        blocks are reclaimable, so they don't count as used)."""
        return self.cfg.usable_blocks - len(self._free) - len(self._lru)

    @property
    def cached_blocks(self) -> int:
        """Content-addressed blocks with no live reference (the LRU set)."""
        return len(self._lru)

    @property
    def utilization(self) -> float:
        return self.used_blocks / max(1, self.cfg.usable_blocks)

    def block_table(self, seq_id, pad_to: int) -> np.ndarray:
        """[pad_to] int32 table, unallocated tail padded with garbage
        block 0 (those positions are always masked)."""
        table = self._tables[seq_id]
        if len(table) > pad_to:
            raise ValueError(
                f"sequence {seq_id!r} holds {len(table)} blocks, "
                f"table was asked to fit in {pad_to}"
            )
        out = np.zeros((pad_to,), np.int32)
        out[: len(table)] = table
        return out

    def table_version(self, seq_id) -> int:
        """Monotonic per-sequence counter, bumped on any table-content
        change — cache key for host-side materialized block tables."""
        return self._versions[seq_id]

    def debug_snapshot(self) -> dict:
        """JSON-safe accounting snapshot for the engine's flight-recorder
        / debug dumps — block-pool state plus the cumulative CacheStats
        counters, no device arrays."""
        s = self.stats
        return {
            "num_blocks": self.cfg.num_blocks,
            "block_size": self.cfg.block_size,
            "used_blocks": self.used_blocks,
            "free_blocks": len(self._free),
            "quarantined_blocks": len(self._quarantine),
            "cached_blocks": self.cached_blocks,
            "reserved_blocks": self._reserved,
            "live_sequences": len(self._tables),
            "utilization": round(self.utilization, 4),
            "high_water_blocks": s.high_water_blocks,
            "allocated_total": s.allocated_total,
            "freed_total": s.freed_total,
            "prefix_hit_blocks": s.prefix_hit_blocks,
            "prefix_hit_tokens": s.prefix_hit_tokens,
            "prefix_evicted_blocks": s.prefix_evicted_blocks,
            "cow_copies": s.cow_copies,
            "adopted_blocks": s.adopted_blocks,
        }

    def num_allocated(self, seq_id) -> int:
        return len(self._tables[seq_id])
