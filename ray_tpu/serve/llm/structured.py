"""Grammar-constrained decoding: spec -> token-level DFA -> allow-masks.

This module is the host-side half of structured output (ISSUE 16 /
ROADMAP 4(a)). It compiles a grammar spec — JSON mode, a JSON-Schema
subset, or a regex subset — into a byte-level DFA whose per-state
token allow-sets are precomputed as a packed ``[S, ceil(V/32)]``
uint32 bitmask table, built once per ``(grammar, vocab, eos)`` and
LRU-cached process-wide. Per-request :class:`FSMCursor` objects then
advance on the engine's already-synced host token ids — the cursors
never touch a jax value, so the engine's single device->host sync
point (``_host_tokens``) is unchanged and the sanitizer host-sync
lint covers this file.

Design constraints:

- **Bytes are tokens.** The serving tokenizer is byte-level
  (``api.encode_text``: token id t < 256 <-> UTF-8 byte t), so the
  DFA alphabet is ``min(256, vocab_size)`` and token ids outside it
  are never allowed by a constrained row.
- **Mask is data, not signature.** The engine stages one packed
  uint32 row per batch slot into the ``sample=`` pytree every step
  (all-ones for unconstrained rows), so constrained and unconstrained
  rows share one decode program and the compile-kind set is frozen.
- **Unsatisfiable is a client error.** A grammar with no accepting
  path within the vocabulary raises :class:`GrammarError` — a
  ``ValueError`` subclass the proxies map to 400/INVALID_ARGUMENT,
  never a 500.
- **EOS is the DFA's terminal.** Accepting states allow ``eos_id``;
  accepting states with no outgoing byte edge are ``must_stop`` and
  the engine completes the stream there exactly like EOS.
"""
from __future__ import annotations

import json
import logging
import threading
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from ray_tpu.serve.llm import obs
from ray_tpu.util import metrics

logger = logging.getLogger("ray_tpu.serve.llm")

# Compile-time caps: DFA state blowup and {m,n} repetition expansion
# both raise GrammarError rather than wedging the submit path.
_DFA_STATE_CAP = 4096
_NFA_STATE_CAP = 200_000
_REP_CAP = 512
_JSON_DEPTH = 3

GRAMMAR_COMPILE_BUCKETS = (
    0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0,
)


def compile_seconds_histogram() -> metrics.Histogram:
    return metrics.histogram(
        "llm_grammar_compile_seconds",
        "Wall time to compile one grammar spec into a token DFA "
        "(cache misses only; hits are O(1))",
        boundaries=GRAMMAR_COMPILE_BUCKETS,
    )


def cache_hit_gauge() -> metrics.Gauge:
    return metrics.gauge(
        "llm_grammar_cache_hit_rate",
        "Lifetime hit rate of the process-wide (grammar, vocab, eos) "
        "-> token-DFA LRU cache",
    )


class GrammarError(ValueError):
    """Invalid, unsupported, or unsatisfiable grammar spec.

    Subclasses ``ValueError`` so the serving proxies map it to a
    client error (HTTP 400 / gRPC INVALID_ARGUMENT), not a 500: a bad
    grammar is the request's fault, and must not trigger failover.
    """


@dataclass(frozen=True)
class GrammarSpec:
    """Canonical grammar spec: ``kind`` in {json, json_schema, regex},
    ``text`` the canonical payload (empty for JSON mode, the
    declaration-order ``json.dumps`` of the schema, or the regex
    pattern). Hashable and picklable — it rides inside
    ``SamplingParams`` across the handle/replica boundary, and is the
    grammar half of the DFA cache key."""

    kind: str
    text: str = ""


def parse_response_format(value) -> GrammarSpec | None:
    """Normalize a ``response_format=`` payload into a GrammarSpec.

    Accepts ``None`` (unconstrained), the strings ``"json"`` /
    ``"json_object"``, a ``GrammarSpec``, or a dict in the OpenAI
    shapes::

        {"type": "json_object"}
        {"type": "json_schema", "json_schema": {"schema": {...}}}
        {"type": "json_schema", "schema": {...}}
        {"type": "regex", "pattern": "..."}

    Anything else raises :class:`GrammarError`.
    """
    if value is None:
        return None
    if isinstance(value, GrammarSpec):
        if value.kind not in ("json", "json_schema", "regex"):
            raise GrammarError(
                f"unknown grammar kind {value.kind!r}; expected "
                "json, json_schema or regex"
            )
        return value
    if isinstance(value, str):
        if value in ("json", "json_object"):
            return GrammarSpec(kind="json")
        raise GrammarError(
            f"unknown response_format {value!r}; expected 'json' or "
            "'json_object'"
        )
    if isinstance(value, dict):
        kind = value.get("type")
        if kind in ("json", "json_object"):
            return GrammarSpec(kind="json")
        if kind == "json_schema":
            schema = value.get("schema")
            if schema is None:
                wrapper = value.get("json_schema")
                if isinstance(wrapper, dict):
                    schema = wrapper.get("schema")
            if not isinstance(schema, dict):
                raise GrammarError(
                    "response_format type 'json_schema' needs a dict "
                    "schema under 'schema' or 'json_schema.schema'"
                )
            # NOT sort_keys: property order is the emission order, so
            # it is semantically part of the grammar (and the cache key)
            return GrammarSpec(
                kind="json_schema",
                text=json.dumps(schema, separators=(",", ":")),
            )
        if kind == "regex":
            pattern = value.get("pattern", value.get("regex"))
            if not isinstance(pattern, str) or not pattern:
                raise GrammarError(
                    "response_format type 'regex' needs a non-empty "
                    "string 'pattern'"
                )
            return GrammarSpec(kind="regex", text=pattern)
        raise GrammarError(
            f"unknown response_format type {kind!r}; expected "
            "json, json_object, json_schema or regex"
        )
    raise GrammarError(
        f"response_format must be None, str, dict or GrammarSpec, "
        f"got {type(value).__name__}"
    )


# ---------------------------------------------------------------------------
# Regex subset -> AST
#
# Supported: literals (UTF-8, multi-byte chars become byte sequences),
# escapes (\d \D \w \W \s \S \n \r \t \f \v \0 \xHH and escaped
# punctuation), char classes [...] with ranges and ^-negation, ``.``
# (any byte but \n), (?:...) / (...) grouping, ``|`` alternation, and
# the quantifiers * + ? {m} {m,} {m,n}. Anchors, backrefs, lookaround
# and lazy quantifiers are rejected — the output must be a DFA.
# ---------------------------------------------------------------------------

def _byteset() -> np.ndarray:
    return np.zeros(256, dtype=bool)


def _class_escape(c: str) -> np.ndarray:
    """Byteset for a class-style escape letter, or raise."""
    bs = _byteset()
    if c == "d":
        bs[0x30:0x3A] = True
    elif c == "D":
        bs[:] = True
        bs[0x30:0x3A] = False
    elif c == "w":
        bs[0x30:0x3A] = True
        bs[0x41:0x5B] = True
        bs[0x5F] = True
        bs[0x61:0x7B] = True
    elif c == "W":
        bs[:] = True
        bs[0x30:0x3A] = False
        bs[0x41:0x5B] = False
        bs[0x5F] = False
        bs[0x61:0x7B] = False
    elif c == "s":
        for b in (0x20, 0x09, 0x0A, 0x0D, 0x0C, 0x0B):
            bs[b] = True
    elif c == "S":
        bs[:] = True
        for b in (0x20, 0x09, 0x0A, 0x0D, 0x0C, 0x0B):
            bs[b] = False
    else:
        raise GrammarError(f"unsupported escape \\{c}")
    return bs


_CTRL_ESCAPES = {
    "n": 0x0A, "r": 0x0D, "t": 0x09, "f": 0x0C, "v": 0x0B, "0": 0x00,
}


class _Parser:
    """Recursive-descent parser for the regex subset. Produces an AST
    of tuples: ``("lit", byteset)``, ``("cat", [..])``,
    ``("alt", [..])``, ``("rep", node, m, n_or_None)``."""

    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            raise GrammarError(
                f"unexpected {self.p[self.i]!r} at index {self.i}"
            )
        return node

    def _peek(self):
        return self.p[self.i] if self.i < len(self.p) else None

    def _alt(self):
        parts = [self._cat()]
        while self._peek() == "|":
            self.i += 1
            parts.append(self._cat())
        return parts[0] if len(parts) == 1 else ("alt", parts)

    def _cat(self):
        parts = []
        while True:
            c = self._peek()
            if c is None or c in "|)":
                break
            parts.append(self._repeat())
        if len(parts) == 1:
            return parts[0]
        return ("cat", parts)

    def _repeat(self):
        node = self._atom()
        while True:
            c = self._peek()
            if c == "*":
                self.i += 1
                node = ("rep", node, 0, None)
            elif c == "+":
                self.i += 1
                node = ("rep", node, 1, None)
            elif c == "?":
                self.i += 1
                node = ("rep", node, 0, 1)
            elif c == "{":
                node = self._braced(node)
            else:
                return node

    def _braced(self, node):
        j = self.p.find("}", self.i)
        if j < 0:
            raise GrammarError("unterminated {m,n} quantifier")
        body = self.p[self.i + 1 : j]
        self.i = j + 1
        try:
            if "," in body:
                lo, hi = body.split(",", 1)
                m = int(lo) if lo.strip() else 0
                n = int(hi) if hi.strip() else None
            else:
                m = n = int(body)
        except ValueError as e:
            raise GrammarError(f"bad quantifier {{{body}}}") from e
        if m < 0 or (n is not None and n < m):
            raise GrammarError(f"bad quantifier {{{body}}}")
        if m > _REP_CAP or (n is not None and n > _REP_CAP):
            raise GrammarError(
                f"quantifier {{{body}}} exceeds repetition cap {_REP_CAP}"
            )
        return ("rep", node, m, n)

    def _atom(self):
        c = self.p[self.i]
        if c == "(":
            self.i += 1
            if self.p.startswith("?:", self.i):
                self.i += 2
            elif self._peek() == "?":
                raise GrammarError(
                    "only (?:...) groups are supported (no lookaround "
                    "or flags)"
                )
            node = self._alt()
            if self._peek() != ")":
                raise GrammarError("unbalanced '('")
            self.i += 1
            return node
        if c == "[":
            return ("lit", self._class())
        if c == ".":
            self.i += 1
            bs = _byteset()
            bs[:] = True
            bs[0x0A] = False
            return ("lit", bs)
        if c == "\\":
            return self._escape_atom()
        if c in "*+?{":
            raise GrammarError(f"dangling quantifier {c!r}")
        if c in "^$":
            raise GrammarError(f"anchors ({c!r}) are not supported")
        self.i += 1
        return self._char_node(c)

    def _char_node(self, c: str):
        enc = c.encode("utf-8")
        if len(enc) == 1:
            bs = _byteset()
            bs[enc[0]] = True
            return ("lit", bs)
        parts = []
        for b in enc:
            bs = _byteset()
            bs[b] = True
            parts.append(("lit", bs))
        return ("cat", parts)

    def _escape_atom(self):
        self.i += 1  # consume backslash
        if self.i >= len(self.p):
            raise GrammarError("dangling backslash")
        c = self.p[self.i]
        self.i += 1
        if c in "dDwWsS":
            return ("lit", _class_escape(c))
        if c in _CTRL_ESCAPES:
            bs = _byteset()
            bs[_CTRL_ESCAPES[c]] = True
            return ("lit", bs)
        if c == "x":
            hx = self.p[self.i : self.i + 2]
            if len(hx) != 2:
                raise GrammarError("truncated \\xHH escape")
            try:
                b = int(hx, 16)
            except ValueError as e:
                raise GrammarError(f"bad \\x{hx} escape") from e
            self.i += 2
            bs = _byteset()
            bs[b] = True
            return ("lit", bs)
        if c.isalnum():
            raise GrammarError(f"unsupported escape \\{c}")
        return self._char_node(c)

    def _class_member(self) -> tuple[np.ndarray, int | None]:
        """One class member: (byteset, single_byte_or_None). Ranges
        need the single-byte form on both ends."""
        c = self.p[self.i]
        if c == "\\":
            self.i += 1
            if self.i >= len(self.p):
                raise GrammarError("dangling backslash in class")
            e = self.p[self.i]
            self.i += 1
            if e in "dDwWsS":
                return _class_escape(e), None
            if e in _CTRL_ESCAPES:
                b = _CTRL_ESCAPES[e]
                bs = _byteset()
                bs[b] = True
                return bs, b
            if e == "x":
                hx = self.p[self.i : self.i + 2]
                if len(hx) != 2:
                    raise GrammarError("truncated \\xHH escape in class")
                try:
                    b = int(hx, 16)
                except ValueError as ex:
                    raise GrammarError(f"bad \\x{hx} escape") from ex
                self.i += 2
                bs = _byteset()
                bs[b] = True
                return bs, b
            if e.isalnum():
                raise GrammarError(f"unsupported escape \\{e} in class")
            c = e
        else:
            self.i += 1
        enc = c.encode("utf-8")
        if len(enc) != 1:
            raise GrammarError(
                f"non-ASCII char {c!r} in class (byte-level alphabet)"
            )
        bs = _byteset()
        bs[enc[0]] = True
        return bs, enc[0]

    def _class(self) -> np.ndarray:
        self.i += 1  # consume '['
        negate = False
        if self._peek() == "^":
            negate = True
            self.i += 1
        acc = _byteset()
        first = True
        while True:
            c = self._peek()
            if c is None:
                raise GrammarError("unterminated character class")
            if c == "]" and not first:
                self.i += 1
                break
            first = False
            bs, lo = self._class_member()
            if (
                lo is not None
                and self._peek() == "-"
                and self.i + 1 < len(self.p)
                and self.p[self.i + 1] != "]"
            ):
                self.i += 1  # consume '-'
                _, hi = self._class_member()
                if hi is None or hi < lo:
                    raise GrammarError("bad range in character class")
                acc[lo : hi + 1] = True
            else:
                acc |= bs
        if negate:
            acc = ~acc
        if not acc.any():
            raise GrammarError("empty character class")
        return acc


# ---------------------------------------------------------------------------
# AST -> Thompson NFA -> subset-construction DFA
# ---------------------------------------------------------------------------

class _NFA:
    def __init__(self):
        self.eps: list[list[int]] = []
        self.edges: list[list[tuple[np.ndarray, int]]] = []

    def new(self) -> int:
        if len(self.eps) >= _NFA_STATE_CAP:
            raise GrammarError(
                f"grammar too large: NFA exceeds {_NFA_STATE_CAP} states"
            )
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1


def _build_nfa(node, nfa: _NFA) -> tuple[int, int]:
    tag = node[0]
    if tag == "lit":
        s = nfa.new()
        e = nfa.new()
        nfa.edges[s].append((node[1], e))
        return s, e
    if tag == "cat":
        if not node[1]:
            s = nfa.new()
            return s, s
        s, e = _build_nfa(node[1][0], nfa)
        for sub in node[1][1:]:
            s2, e2 = _build_nfa(sub, nfa)
            nfa.eps[e].append(s2)
            e = e2
        return s, e
    if tag == "alt":
        s = nfa.new()
        e = nfa.new()
        for sub in node[1]:
            s2, e2 = _build_nfa(sub, nfa)
            nfa.eps[s].append(s2)
            nfa.eps[e2].append(e)
        return s, e
    if tag == "rep":
        _, sub, m, n = node
        s = nfa.new()
        cur = s
        for _ in range(m):
            s2, e2 = _build_nfa(sub, nfa)
            nfa.eps[cur].append(s2)
            cur = e2
        end = nfa.new()
        if n is None:
            s2, e2 = _build_nfa(sub, nfa)
            nfa.eps[cur].append(s2)
            nfa.eps[cur].append(end)
            nfa.eps[e2].append(s2)
            nfa.eps[e2].append(end)
        else:
            nfa.eps[cur].append(end)
            for _ in range(n - m):
                s2, e2 = _build_nfa(sub, nfa)
                nfa.eps[cur].append(s2)
                cur = e2
                nfa.eps[cur].append(end)
        return s, end
    raise GrammarError(f"internal: unknown AST node {tag!r}")


def _closure(nfa: _NFA, states) -> frozenset:
    seen = set(states)
    stack = list(states)
    while stack:
        s = stack.pop()
        for t in nfa.eps[s]:
            if t not in seen:
                seen.add(t)
                stack.append(t)
    return frozenset(seen)


def _subset_construct(
    nfa: _NFA, start: int, accept_nfa: int, alphabet: int
) -> tuple[np.ndarray, np.ndarray]:
    """NFA -> DFA over bytes ``[0, alphabet)``. Returns
    ``(trans [S,256] int32 with -1 = reject, accept [S] bool)``."""
    start_set = _closure(nfa, [start])
    index: dict[frozenset, int] = {start_set: 0}
    order = [start_set]
    rows: list[np.ndarray] = []
    i = 0
    while i < len(order):
        dstate = order[i]
        i += 1
        row = np.full(256, -1, dtype=np.int32)
        edge_sets: list[np.ndarray] = []
        edge_targets: list[int] = []
        for s in dstate:
            for bs, t in nfa.edges[s]:
                edge_sets.append(bs)
                edge_targets.append(t)
        if edge_sets:
            m = np.zeros((len(edge_sets), 256), dtype=bool)
            for j, bs in enumerate(edge_sets):
                m[j] = bs
            m[:, alphabet:] = False
            # group the 256 byte columns into equivalence classes so
            # the closure work is O(#classes), not O(256)
            cols = np.packbits(m, axis=0)
            _, inv = np.unique(cols, axis=1, return_inverse=True)
            inv = inv.reshape(-1)
            for u in range(int(inv.max()) + 1):
                class_bytes = np.nonzero(inv == u)[0]
                b0 = int(class_bytes[0])
                active = [
                    edge_targets[j]
                    for j in range(len(edge_sets))
                    if m[j, b0]
                ]
                if not active:
                    continue
                tset = _closure(nfa, active)
                nxt = index.get(tset)
                if nxt is None:
                    if len(order) >= _DFA_STATE_CAP:
                        raise GrammarError(
                            "grammar too large: DFA exceeds "
                            f"{_DFA_STATE_CAP} states"
                        )
                    nxt = len(order)
                    index[tset] = nxt
                    order.append(tset)
                row[class_bytes] = nxt
        rows.append(row)
    S = len(order)
    trans = np.zeros((S, 256), dtype=np.int32)
    for k, row in enumerate(rows):
        trans[k] = row
    accept = np.zeros(S, dtype=bool)
    for k, dstate in enumerate(order):
        accept[k] = accept_nfa in dstate
    return trans, accept


def _trim(trans: np.ndarray, accept: np.ndarray):
    """Drop states that cannot reach an accepting state (their rows
    would stage all-banned masks); raise if the start state is one —
    that grammar is unsatisfiable within the vocabulary."""
    S = trans.shape[0]
    radj: list[list[int]] = [[] for _ in range(S)]
    for s in range(S):
        for t in set(int(x) for x in trans[s] if x >= 0):
            radj[t].append(s)
    co = set(int(x) for x in np.nonzero(accept)[0])
    stack = list(co)
    while stack:
        t = stack.pop()
        for s in radj[t]:
            if s not in co:
                co.add(s)
                stack.append(s)
    if 0 not in co:
        raise GrammarError(
            "unsatisfiable grammar: no accepting path exists within "
            "the model's vocabulary"
        )
    keep = sorted(co)
    remap = np.full(S + 1, -1, dtype=np.int32)
    for new, old in enumerate(keep):
        remap[old] = new
    new_trans = remap[trans[keep]]  # trans == -1 hits remap[-1] == -1
    new_accept = accept[keep]
    return new_trans, new_accept


# ---------------------------------------------------------------------------
# JSON mode / JSON-Schema subset -> regex pattern
# ---------------------------------------------------------------------------

# Compact JSON, no inter-token whitespace. Strings are printable ASCII
# minus '"' and '\', plus the single-char escapes (no \uXXXX).
_STR_RE = r'"(?:[\x20-\x21\x23-\x5b\x5d-\x7e]|\\["\\/bfnrt])*"'
_INT_RE = r"-?(?:0|[1-9][0-9]*)"
_NUM_RE = _INT_RE + r"(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?"
_SCHEMA_DEPTH_CAP = 12


def _json_value_regex(depth: int) -> str:
    atoms = ["null", "true", "false", _NUM_RE, _STR_RE]
    if depth > 0:
        inner = _json_value_regex(depth - 1)
        atoms.append(r"\[(?:%s(?:,%s)*)?\]" % (inner, inner))
        atoms.append(
            r"\{(?:%s:%s(?:,%s:%s)*)?\}" % (_STR_RE, inner, _STR_RE, inner)
        )
    return "(?:" + "|".join(atoms) + ")"


def _json_mode_regex() -> str:
    """JSON mode: one object whose values nest up to _JSON_DEPTH deep
    (matching ``{"type": "json_object"}`` semantics)."""
    inner = _json_value_regex(_JSON_DEPTH - 1)
    return r"\{(?:%s:%s(?:,%s:%s)*)?\}" % (_STR_RE, inner, _STR_RE, inner)


def _lit_regex(text: str) -> str:
    out = []
    for c in text:
        if c.isalnum():
            out.append(c)
        else:
            out.append("\\" + c)
    return "".join(out)


def _schema_regex(schema, depth: int = 0) -> str:
    """JSON-Schema subset -> regex. Objects emit their declared
    properties in order, all required; supported keywords: type
    (object/array/string/integer/number/boolean/null), properties,
    items, minItems/maxItems, enum, const, anyOf/oneOf."""
    if depth > _SCHEMA_DEPTH_CAP:
        raise GrammarError(
            f"schema nesting exceeds depth cap {_SCHEMA_DEPTH_CAP}"
        )
    if not isinstance(schema, dict):
        raise GrammarError(
            f"schema must be a dict, got {type(schema).__name__}"
        )
    if "const" in schema:
        return _lit_regex(json.dumps(schema["const"], separators=(",", ":")))
    if "enum" in schema:
        vals = schema["enum"]
        if not isinstance(vals, list) or not vals:
            raise GrammarError("'enum' must be a non-empty list")
        return "(?:" + "|".join(
            _lit_regex(json.dumps(v, separators=(",", ":"))) for v in vals
        ) + ")"
    for combo in ("anyOf", "oneOf"):
        if combo in schema:
            subs = schema[combo]
            if not isinstance(subs, list) or not subs:
                raise GrammarError(f"{combo!r} must be a non-empty list")
            return "(?:" + "|".join(
                _schema_regex(s, depth + 1) for s in subs
            ) + ")"
    t = schema.get("type")
    if t == "object":
        props = schema.get("properties", {})
        if not isinstance(props, dict):
            raise GrammarError("'properties' must be a dict")
        if not props:
            return r"\{\}"
        fields = [
            '\\"%s\\":%s'
            % (_escape_json_string(k), _schema_regex(v, depth + 1))
            for k, v in props.items()
        ]
        return r"\{" + ",".join(fields) + r"\}"
    if t == "array":
        items = schema.get("items")
        if items is None:
            raise GrammarError("array schema needs 'items'")
        item = _schema_regex(items, depth + 1)
        lo = schema.get("minItems", 0)
        hi = schema.get("maxItems", max(int(lo), 1) + 2)
        if not (isinstance(lo, int) and isinstance(hi, int)) or lo < 0:
            raise GrammarError("minItems/maxItems must be ints >= 0")
        if hi < lo:
            raise GrammarError("maxItems < minItems")
        if hi == 0:
            return r"\[\]"
        if lo == 0:
            return r"\[(?:%s(?:,%s){0,%d})?\]" % (item, item, hi - 1)
        return r"\[%s(?:,%s){%d,%d}\]" % (item, item, lo - 1, hi - 1)
    if t == "string":
        return _STR_RE
    if t == "integer":
        return _INT_RE
    if t == "number":
        return _NUM_RE
    if t == "boolean":
        return "(?:true|false)"
    if t == "null":
        return "null"
    raise GrammarError(f"unsupported schema: {schema!r}")


def _escape_json_string(key: str) -> str:
    """Regex for the *contents* of a JSON object key (between the
    quotes): the key chars, regex-escaped, with JSON-special chars
    rejected (they would need escape-sequence emission)."""
    for c in key:
        if ord(c) < 0x20 or c in ('"', "\\") or ord(c) > 0x7E:
            raise GrammarError(
                f"unsupported character {c!r} in property name {key!r}"
            )
    return _lit_regex(key)


# ---------------------------------------------------------------------------
# Token DFA + per-request cursor
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class TokenDFA:
    """A compiled grammar over token ids.

    - ``trans``: ``[S, 256]`` int32; ``trans[s, t]`` is the next state
      on token t, or -1 (reject). Token ids >= 256 always reject.
    - ``accept``: ``[S]`` bool — the byte prefix so far is a complete
      sentence of the grammar.
    - ``mask``: ``[S, ceil(V/32)]`` uint32, little-endian packed (bit
      j of word w = token ``w*32+j``); the per-state allow-set with
      the EOS bit set at accepting states. Rows are staged directly
      into the engine's ``sample=`` scratch.
    - ``allowed_counts``: ``[S]`` int32 popcounts of ``mask`` (for the
      masked-fraction metric, O(1) per step).
    - ``must_stop``: ``[S]`` bool — accepting with no outgoing edge;
      the engine completes the stream there like EOS.
    """

    trans: np.ndarray
    accept: np.ndarray
    mask: np.ndarray
    allowed_counts: np.ndarray
    must_stop: np.ndarray
    vocab_size: int
    eos_id: int | None
    words: int

    @property
    def n_states(self) -> int:
        return int(self.trans.shape[0])


def _token_table(
    trans: np.ndarray,
    accept: np.ndarray,
    vocab_size: int,
    eos_id: int | None,
) -> TokenDFA:
    S = trans.shape[0]
    V = int(vocab_size)
    words = (V + 31) // 32
    limit = min(256, V)
    allow = np.zeros((S, words * 32), dtype=np.uint32)
    allow[:, :limit] = trans[:, :limit] >= 0
    if eos_id is not None and 0 <= eos_id < V:
        allow[accept, eos_id] = 1
    counts = allow.sum(axis=1).astype(np.int32)
    weights = np.uint32(1) << np.arange(32, dtype=np.uint32)
    packed = (
        (allow.reshape(S, words, 32).astype(np.uint64) * weights)
        .sum(axis=2)
        .astype(np.uint32)
    )
    out_any = (trans[:, :limit] >= 0).any(axis=1)
    must_stop = accept & ~out_any
    return TokenDFA(
        trans=trans,
        accept=accept,
        mask=packed,
        allowed_counts=counts,
        must_stop=must_stop,
        vocab_size=V,
        eos_id=eos_id,
        words=words,
    )


class FSMCursor:
    """Per-request position in a TokenDFA. Host-only: advances on the
    already-synced int token ids the engine hands it — never on a jax
    value — so constrained decoding adds zero device->host syncs."""

    __slots__ = ("dfa", "state", "dead")

    def __init__(self, dfa: TokenDFA):
        self.dfa = dfa
        self.state = 0
        self.dead = False

    def advance(self, tok: int) -> bool:
        """Consume one emitted token; False = the grammar rejects it
        (the cursor goes dead and the stream must terminate)."""
        if self.dead:
            return False
        if tok < 0 or tok >= self.dfa.trans.shape[1]:
            self.dead = True
            return False
        nxt = int(self.dfa.trans[self.state, tok])
        if nxt < 0:
            self.dead = True
            return False
        self.state = nxt
        return True

    @property
    def must_stop(self) -> bool:
        return bool(self.dfa.must_stop[self.state])

    @property
    def accepting(self) -> bool:
        return bool(self.dfa.accept[self.state])

    def allow_row(self) -> np.ndarray:
        """Packed uint32 ``[words]`` allow-mask for the current state
        (a view into the shared table — copy-on-stage by the engine's
        scratch assignment)."""
        return self.dfa.mask[self.state]

    def masked_fraction(self) -> float:
        """Fraction of the vocab banned at the current state."""
        allowed = float(self.dfa.allowed_counts[self.state])
        return 1.0 - allowed / float(self.dfa.vocab_size)

    def filter_draft(self, tokens) -> list[int]:
        """Longest grammar-valid prefix of a speculative draft from
        the current state (truncating before any EOS — EOS ends the
        stream at emit time, not inside a verify window). The cursor
        itself does not move; committed tokens advance it via
        ``advance`` at the emit path like every other token."""
        dfa = self.dfa
        st = self.state
        out: list[int] = []
        for t in tokens:
            t = int(t)
            if dfa.eos_id is not None and t == dfa.eos_id:
                break
            if t < 0 or t >= dfa.trans.shape[1]:
                break
            nxt = int(dfa.trans[st, t])
            if nxt < 0:
                break
            out.append(t)
            st = nxt
        return out

    def stage_verify_masks(self, out: np.ndarray, draft) -> None:
        """Fill ``out[W, words]`` with per-column allow-masks for a
        verify window: column 0 is the current state's mask, column s
        the mask after consuming ``draft[:s]``. Columns past the draft
        length hold the last simulated state (those positions never
        commit — acceptance stops at the first mismatch)."""
        dfa = self.dfa
        st = self.state
        out[0] = dfa.mask[st]
        for s in range(1, out.shape[0]):
            if s - 1 < len(draft):
                t = int(draft[s - 1])
                if 0 <= t < dfa.trans.shape[1]:
                    nxt = int(dfa.trans[st, t])
                    if nxt >= 0:
                        st = nxt
            out[s] = dfa.mask[st]


# ---------------------------------------------------------------------------
# Compile + process-wide LRU cache
# ---------------------------------------------------------------------------

_CACHE_CAP = 64
_cache: OrderedDict[tuple, TokenDFA] = OrderedDict()
_cache_lock = threading.Lock()
_cache_stats = {"lookups": 0, "hits": 0}


def _compile(spec: GrammarSpec, vocab_size: int, eos_id) -> TokenDFA:
    if spec.kind == "json":
        pattern = _json_mode_regex()
    elif spec.kind == "json_schema":
        pattern = _schema_regex(json.loads(spec.text))
    elif spec.kind == "regex":
        pattern = spec.text
    else:
        raise GrammarError(f"unknown grammar kind {spec.kind!r}")
    ast = _Parser(pattern).parse()
    nfa = _NFA()
    start, end = _build_nfa(ast, nfa)
    alphabet = min(256, int(vocab_size))
    trans, accept = _subset_construct(nfa, start, end, alphabet)
    trans, accept = _trim(trans, accept)
    return _token_table(trans, accept, vocab_size, eos_id)


def cache_stats() -> dict:
    with _cache_lock:
        return {
            "size": len(_cache),
            "lookups": _cache_stats["lookups"],
            "hits": _cache_stats["hits"],
        }


def clear_cache() -> None:
    """Test hook: drop all compiled DFAs (and the hit-rate history)."""
    with _cache_lock:
        _cache.clear()
        _cache_stats["lookups"] = 0
        _cache_stats["hits"] = 0


def compile_grammar(
    spec: GrammarSpec, vocab_size: int, eos_id: int | None = None
) -> TokenDFA:
    """Grammar spec -> TokenDFA, LRU-cached on
    ``(kind, text, vocab_size, eos_id)``.

    Raises :class:`GrammarError` (a ``ValueError``) for invalid,
    unsupported, oversized, or unsatisfiable grammars — the proxies
    map it to a client error; it must never crash the engine or look
    retryable to the handle.
    """
    key = (spec.kind, spec.text, int(vocab_size), eos_id)
    with _cache_lock:
        _cache_stats["lookups"] += 1
        dfa = _cache.get(key)
        if dfa is not None:
            _cache.move_to_end(key)
            _cache_stats["hits"] += 1
            cache_hit_gauge().set(
                _cache_stats["hits"] / _cache_stats["lookups"]
            )
            return dfa
    t0 = obs.clock()
    try:
        dfa = _compile(spec, vocab_size, eos_id)
    except GrammarError:
        raise
    except (ValueError, KeyError, TypeError, RecursionError) as e:
        # degradation path is loud by contract: a compile failure is
        # re-raised as the client-visible GrammarError, never swallowed
        raise GrammarError(f"grammar compile failed: {e!r}") from e
    compile_seconds_histogram().observe(obs.clock() - t0)
    with _cache_lock:
        _cache[key] = dfa
        while len(_cache) > _CACHE_CAP:
            _cache.popitem(last=False)
        cache_hit_gauge().set(
            _cache_stats["hits"] / max(1, _cache_stats["lookups"])
        )
    logger.info(
        "compiled grammar kind=%s states=%d vocab=%d",
        spec.kind, dfa.n_states, int(vocab_size),
    )
    return dfa
