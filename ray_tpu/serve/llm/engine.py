"""Continuous-batching scheduler: admission, chunked-prefill/decode
interleave, prefix-cache reuse, per-step join/evict, bucketed shapes.

The loop is the Orca/vLLM iteration-level scheduler: every step is EITHER
one batched prefill CHUNK (new admissions, or the next slice of a long
prompt) or one batched decode step over all running sequences — new
requests join the decode batch at the step after their prefill completes,
finished sequences leave it the step they complete, and their KV blocks
return to the pool immediately.

Two serving-throughput optimizations sit on top of PR 1/2's engine:

- **Prefix caching** (SGLang's RadixAttention idea at block granularity):
  admission maps the longest content-addressed full-block prefix of a new
  prompt onto blocks already resident in the paged cache, so shared system
  prompts / few-shot headers cost ZERO prefill compute on repeat traffic.
  Shared blocks are refcounted and copy-on-write; unreferenced cached
  blocks are evicted LRU when the free list runs dry (kv_cache.py).
- **Chunked prefill**: a prompt's uncached suffix is prefilled in
  ``prefill_chunk_tokens``-sized bucketed slices, and the scheduler
  ALTERNATES prefill chunks with decode steps, so a long new prompt never
  head-of-line-blocks tokens streaming from running sequences.

TPU-first constraint: every jitted call's shape is drawn from a closed
set. Batch sizes pad to ``batch_buckets`` and token/context lengths to
``length_buckets`` (serve/_shapes.py pad_to_bucket — the same rule the
@serve.batch router uses), so compiled programs stay bounded no matter
the traffic mix (arxiv 2011.03641: static-shape batching to stay inside
the compile cache). Chunk prefills reuse the SAME length buckets for both
the chunk width and the context extent, so they add at most one more
bounded signature family ("prefill_chunk") next to the monolithic
"prefill" and "decode" kinds. `DecodeFns.num_compiled_shapes` reports the
realized count.

Sampling is FUSED into the jitted model step (ops/sampling.py): greedy,
temperature, top-k and top-p all run on device, so the per-token
device->host transfer is O(batch) int32 token ids instead of
O(batch x vocab) float32 logits. Per-token randomness is keyed, not
stateful: token position p draws from
``fold_in(PRNGKey(request_seed), p)``, making every sampled token a pure
function of (logits, seed, position). A sequence's output is therefore
identical whether it ran solo or continuously batched with arbitrary
neighbors, and mid-stream failover is byte-identical BY CONSTRUCTION — a
resumed request re-prefills ``prompt + delivered`` and the keyed draws
at the remaining positions are unchanged (this replaces the old
host-side "burn one numpy uniform per token" RNG contract).

The decode loop is pipelined with a one-step sync lag (dispatch-ahead,
arXiv 2011.03641): step N+1's decode feeds DIRECTLY from step N's
on-device sampled-token array, and the host syncs token ids one step
behind, so bucketing, block-table/COW assembly and scheduler work hide
under device compute via JAX async dispatch. Terminal conditions (EOS,
max_tokens, cancel, deadline) are reconciled when the lagged tokens
arrive — at most one wasted speculative row per just-finished request —
and KV blocks freed while a dispatch is in flight are quarantined until
the next sync proves the dispatch executed (kv_cache.flush_quarantine).

Everything device-side sits behind the ModelExecutor seam (executor.py):
the scheduler stages numpy, the executor owns weights, the paged KV pool
arrays, and the jitted calls. Single-device by default; EngineConfig
``tp``/``fsdp``/``mesh`` select the tp/fsdp-sharded executor without any
scheduler change (docs/SERVING_LLM.md "Sharded serving").

Failure semantics (docs/SERVING_LLM.md "Failure semantics"):

- ``submit`` applies admission control: a bounded waiting queue
  (``max_waiting``) and an optional worst-case block budget for queued
  work (``max_waiting_blocks``), rejecting with ``EngineOverloadedError``
  rather than queueing unboundedly. When the HEAD of the queue doesn't
  fit, admission probes up to ``admission_probe`` smaller requests behind
  it (bounded skip-ahead), with an aging cap (``admission_max_skips``) so
  a large prompt cannot be starved forever.
- per-request deadlines (``SamplingParams.deadline_s``) are enforced at
  the top of every step; expired sequences are evicted and their streams
  fail with ``DeadlineExceededError``.
- ``cancel(request_id)`` evicts a waiting, prefilling, or running
  sequence and returns its KV blocks (allocation AND leftover
  reservation) immediately.
- if a step raises, or wedges past ``step_timeout_s`` (watchdog thread),
  the engine fails closed: every in-flight stream gets an
  ``EngineDiedError`` (an ``ActorError`` — clients treat it exactly like
  replica death and fail over) instead of blocking forever.
"""
from __future__ import annotations

import logging
import math
import queue
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

from ray_tpu._private import chaos, event_stats
from ray_tpu.exceptions import (
    DeadlineExceededError,
    EngineDiedError,
    EngineOverloadedError,
    RequestCancelledError,
)
from ray_tpu.serve._shapes import pad_to_bucket, pow2_buckets
from ray_tpu.serve.llm import obs, structured
from ray_tpu.serve.llm.executor import build_executor
from ray_tpu.serve.llm.kv_cache import KVCacheConfig, PagedKVCache, _block_key
from ray_tpu.util import metrics, tracing

logger = logging.getLogger("ray_tpu.serve.llm")

_DONE = object()  # stream sentinel

# Window (obs.clock seconds) over which autoscaling_snapshot() turns
# deadline-miss / rejection event timestamps into rates.
_SIGNAL_RATE_WINDOW_S = 30.0

# Window (obs.clock seconds) of per-step (device-time, tokens) samples
# behind the llm_goodput_tokens_per_sec / llm_serving_mfu gauges.
_GOODPUT_WINDOW_S = 30.0


def _pctile(samples, q: float) -> float:
    """Nearest-rank percentile of a small sample window; 0.0 when empty."""
    if not samples:
        return 0.0
    xs = sorted(samples)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def _window_rate(clocks: deque, now: float) -> float:
    """Events/second over the trailing window; prunes expired entries."""
    while clocks and now - clocks[0] > _SIGNAL_RATE_WINDOW_S:
        clocks.popleft()
    return len(clocks) / _SIGNAL_RATE_WINDOW_S


# sanity ceiling for max_new_tokens: far above any model's max_seq_len
# (which submit() checks against anyway) but low enough to catch sign
# bugs and unit mistakes at construction time, where the field is named
_MAX_NEW_TOKENS_CAP = 1 << 20

# Priority classes, lowest rank first. Preemption pauses low-rank running
# streams to make room for high-rank waiting ones; shedding degrades in
# the same order (batch sheds before default sheds before interactive).
_PRIORITIES = ("batch", "default", "interactive")
PRIORITY_RANK = {name: rank for rank, name in enumerate(_PRIORITIES)}


@dataclass(frozen=True)
class SamplingParams:
    max_new_tokens: int = 16
    temperature: float = 0.0  # 0 -> greedy
    top_k: int = 0            # 0 or -1 -> full distribution
    top_p: float = 1.0        # nucleus mass in (0, 1]; 1.0 -> disabled
    seed: int = 0
    deadline_s: float | None = None  # wall-clock budget from submit()
    start_index: int = 0      # tokens already delivered (failover resume)
    # grammar constraint (serve/llm/structured.py): None, "json" /
    # "json_object", a response_format dict, or a GrammarSpec
    structured: Any = None
    # stop sequences: token-id sequences that terminate the stream when
    # they appear as a suffix of the generated tokens (the matched stop
    # tokens ARE emitted, like EOS). Normalized to a tuple of tuples.
    stop: Any = ()
    # priority class: "interactive" | "default" | "batch". Orders both
    # preemption (batch pauses first) and class-aware shedding. Never
    # changes tokens — only scheduling order.
    priority: str = "default"

    def __post_init__(self):
        if self.priority not in _PRIORITIES:
            raise ValueError(
                f"priority must be one of {_PRIORITIES}, "
                f"got {self.priority!r}"
            )
        if not (1 <= self.max_new_tokens <= _MAX_NEW_TOKENS_CAP):
            raise ValueError(
                f"max_new_tokens must be in [1, {_MAX_NEW_TOKENS_CAP}], "
                f"got {self.max_new_tokens}"
            )
        if self.start_index < 0:
            raise ValueError("start_index must be >= 0")
        if not math.isfinite(self.temperature) or self.temperature < 0:
            raise ValueError(
                f"temperature must be finite and >= 0, got "
                f"{self.temperature}"
            )
        if self.top_k < -1:
            raise ValueError(
                f"top_k must be >= -1 (0 or -1 disables), got {self.top_k}"
            )
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(
                f"top_p must be in (0, 1], got {self.top_p}"
            )
        norm = []
        for seq in self.stop:
            if isinstance(seq, int):
                seq = (seq,)
            seq = tuple(int(t) for t in seq)
            if not seq:
                raise ValueError("stop sequences must be non-empty")
            norm.append(seq)
        object.__setattr__(self, "stop", tuple(norm))


@dataclass(frozen=True)
class EngineConfig:
    model: str = "llama"          # gpt | llama (decode.py FAMILIES)
    model_config: Any = None      # GPTConfig/LlamaConfig; None -> .tiny()
    block_size: int = 16
    num_blocks: int = 64
    max_batch_size: int = 8       # max concurrently-running sequences
    max_prefill_batch: int = 4    # max requests coalesced into one prefill
    batch_buckets: tuple[int, ...] | None = None   # None -> pow2 ladder
    length_buckets: tuple[int, ...] | None = None  # None -> pow2 ladder
    eos_id: int | None = None
    seed: int = 0                 # param init seed (when params not given)
    max_waiting: int = 128        # admission queue bound (overload beyond)
    max_waiting_blocks: int | None = None  # worst-case block budget queued
    step_timeout_s: float | None = None    # watchdog: wedged-step ceiling
    prefix_caching: bool = True   # map prompts onto resident KV blocks
    # Host-memory KV tier capacity in bytes (0 disables). When set, LRU
    # eviction demotes full prefix blocks into a host arena instead of
    # discarding them, and prefix hits promote them back through the
    # executor's fused land_blocks scatter — see kv_cache.HostKVTier.
    host_cache_bytes: int = 0
    # Prefill one prompt in slices of at most this many tokens, alternating
    # with decode steps. None -> the whole uncached suffix in one call (the
    # monolithic PR 1 behavior for cold prompts).
    prefill_chunk_tokens: int | None = None
    admission_probe: int = 4      # skip-ahead width when the head won't fit
    admission_max_skips: int = 16  # aging cap: stop skipping a starved head
    # Flight recorder: ring of the last N step records, dumped as JSON on
    # EngineDiedError / watchdog timeout / shutdown(dump=...). Dir: None
    # -> $RAY_TPU_FLIGHT_DIR -> <tmp>/ray_tpu_flight (obs.dump_dir).
    flight_recorder_steps: int = 256
    flight_recorder_dir: str | None = None
    # Finished-request timelines kept for request_timeline() lookups.
    timeline_history: int = 256
    # ---- multi-chip sharded serving (executor.py) ----
    # Defaults are single-device (SingleDeviceExecutor — byte-for-byte
    # the pre-seam engine). Widening tp/fsdp, or naming a mesh, selects
    # ShardedExecutor: weights shard tp/fsdp with the training-side
    # rules, the paged KV pool shards along its head axis over tp, and
    # block tables/prefix cache/COW stay host-side.
    # mesh: None | jax.sharding.Mesh | parallel.MeshSpec |
    #       serve.config.ModelParallelConfig | dict of axis sizes.
    mesh: Any = None
    tp: int = 1      # tensor-parallel ways (heads/mlp/vocab + KV heads)
    fsdp: int = 1    # fsdp ways (embed axis of every weight)
    # ---- decode attention backend (ops/paged_attention.py) ----
    # None -> respect the model config's attention_backend (default
    # "auto": the fused Pallas paged-attention kernel on TPU, the XLA
    # gather formulation elsewhere). "xla" | "pallas" force a backend;
    # "auto" forces the platform default. The knob is STATIC in the
    # jitted step (it rides the frozen model config), so switching it
    # never adds a compile kind — signatures stay
    # (prefill, prefill_chunk, decode) x buckets, and token streams are
    # byte-identical across backends (tests/test_paged_attention.py).
    attention_backend: str | None = None
    # ---- quantized serving (ops/quantization.py) ----
    # None -> f32 weights + f32 paged KV (every prior PR's behavior,
    # byte-identical). "int8" | "fp8" quantize BOTH the serving weights
    # (per-channel scales, dequantized lazily at each use site) and the
    # paged KV pool (per-(token, kv-head) scales, dequantized in-register
    # inside the Pallas kernels — the pool never materializes f32 in
    # HBM). STATIC: the knob lands in the frozen model config, so a
    # quantized engine is one compile-kind set of its own — no
    # mixed-precision traffic, and streams stay byte-identical WITHIN a
    # config across failover/handoff/demote-promote/preempt-resume. The
    # cross-config contract is agreement-rate, not byte-identity
    # (docs/SERVING_LLM.md "Quantized serving").
    quantization: str | None = None
    # ---- speculative decoding (drafter.py + executor.verify_step) ----
    # speculative_k > 0 turns on draft-and-verify: a host-side Drafter
    # proposes up to k tokens per sequence and the target model scores
    # the whole [B, k+1] window in ONE jitted "verify" call, committing
    # an accepted prefix plus one corrected token per step (1..k+1
    # tokens). LOSSLESS by construction: acceptance is exact-match
    # against the keyed (seed, position) sampler, so streams are
    # byte-identical to speculative_k=0 for greedy AND temperature/
    # top-k/top-p (docs/SERVING_LLM.md "Speculative decoding"). The
    # window width k+1 is frozen per engine — per-row draft availability
    # is data, not shape — so speculation adds exactly one compile kind
    # ("verify") x the existing buckets.
    speculative_k: int = 0
    # Drafter | "ngram" | None. "ngram" = the model-free prompt-lookup
    # drafter (drafter.NGramDrafter); None drafts nothing (every
    # speculative step degenerates to a 1-token verify). Only consulted
    # when speculative_k > 0.
    drafter: Any = "ngram"
    # ---- priority preemption (None disables) ----
    # PreemptionConfig (or a dict of its fields). When set, the scheduler
    # may PAUSE the lowest-priority running streams under KV-pool pressure
    # or queue-wait pressure: their full KV block chains demote through
    # the host tier funnel, the request parks in a "preempted" lifecycle
    # state with cursor/timeline/FSM intact, and it resumes automatically
    # (byte-identical, by keyed (seed, position) sampling) when pressure
    # clears or the starvation-aging floor trips.
    preemption: Any = None


@dataclass(frozen=True)
class PreemptionConfig:
    """Thresholds for priority preemption (EngineConfig.preemption).

    Pressure is the fraction of usable KV blocks in use (reservations
    included); all times are engine-clock seconds (obs.clock)."""

    kv_pressure: float = 0.90   # pause when pool pressure crosses this
    queue_wait_s: float = 0.25  # ... or a higher-priority wait exceeds this
    resume_pressure: float = 0.75  # resume parked streams below this
    aging_s: float = 30.0       # starvation floor: waiting/parked this long
    # is boosted above interactive and becomes non-preemptible
    max_preempted: int = 64     # cap on concurrently parked streams


class TokenStream:
    """Iterator over one request's generated token ids, delivered as the
    engine produces them (blocks between tokens; ends at completion)."""

    def __init__(self, request: "_Request"):
        self._request = request

    @property
    def request_id(self):
        return self._request.id

    @property
    def done(self) -> bool:
        return self._request.done

    def __iter__(self):
        while True:
            item = self._request.out.get()
            if item is _DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item


class _Request:
    __slots__ = (
        "id", "prompt", "sampling", "out", "generated",
        "reserved_blocks", "drawn_blocks", "prefill_done", "cached_tokens",
        "started", "skips", "table_np", "table_key", "done", "deadline",
        # dispatch-ahead decode: dispatched-but-unreconciled device steps
        # that include this row, and whether its KV blocks went back to
        # the pool (exactly-once release under the lag)
        "inflight", "blocks_released",
        # grammar-constrained decoding: the request's FSM cursor
        # (structured.FSMCursor) or None when unconstrained
        "fsm",
        # lifecycle observability (ISSUE 4): the phase timeline rides the
        # request, and a stored trace context turns it into spans on finish
        "trace_ctx", "timeline", "submitted_clock", "first_token_clock",
        "last_token_clock", "finish_reason",
        # priority preemption: when paused, the full token chain
        # (prompt + generated) to re-prefill on resume; the park
        # timestamp; how many times this stream has been paused
        "pending_resume", "preempted_clock", "preempt_count",
    )

    def __init__(self, req_id, prompt, sampling: SamplingParams,
                 trace_ctx: dict | None = None):
        self.id = req_id
        self.prompt = list(prompt)
        self.sampling = sampling
        self.trace_ctx = trace_ctx
        # [{"event", "ts"(wall), ...}] — submitted/admitted/prefill chunks/
        # first_token/token/terminal; bounded by the request's own lifetime
        self.timeline: list[dict] = []
        self.submitted_clock: float | None = None
        self.first_token_clock: float | None = None
        self.last_token_clock: float | None = None
        self.finish_reason: str | None = None
        self.out: queue.Queue = queue.Queue()
        self.generated: list[int] = []
        # sampling is keyed by (seed, absolute position) on device — no
        # RNG state to carry or fast-forward; start_index only offsets
        # the stream's public token numbering on failover resume
        self.inflight = 0
        self.blocks_released = False
        self.reserved_blocks = 0
        # blocks this request has consumed from its reservation so far:
        # prefix-cache hits + appended blocks + copy-on-write copies. The
        # leftover (reserved - drawn) is what eviction/completion releases.
        self.drawn_blocks = 0
        self.prefill_done = 0     # prompt tokens whose KV is resident
        self.cached_tokens = 0    # of those, tokens served by prefix hits
        self.started = False      # ran at least one prefill chunk
        self.skips = 0            # admissions that jumped over this head
        self.table_np: np.ndarray | None = None  # cached host block table
        self.table_key: tuple | None = None      # (nb, table_version)
        self.fsm = None  # structured.FSMCursor when grammar-constrained
        self.done = False
        self.pending_resume: list[int] | None = None
        self.preempted_clock: float | None = None
        self.preempt_count = 0
        self.deadline = (
            time.monotonic() + sampling.deadline_s
            if sampling.deadline_s is not None
            else None
        )

    @property
    def total_len(self) -> int:
        return len(self.prompt) + len(self.generated)

    @property
    def prefill_tokens(self) -> list[int]:
        """The token chain prefill must make KV-resident: the prompt, or
        prompt + generated-so-far when resuming from preemption."""
        return (self.pending_resume if self.pending_resume is not None
                else self.prompt)


@dataclass
class _PendingDecode:
    """One dispatched-but-unsynced decode step: the on-device sampled
    tokens [B] int32 (row i belongs to ``batch[i]``; padding rows are
    garbage) and the exact batch list it was dispatched over. The steady
    state keeps exactly one of these in flight — step N+1 feeds from
    ``tokens`` directly and the host syncs N's ids one step behind."""

    tokens: Any          # jax [B] int32, still on device
    batch: list          # the _Request rows of this dispatch, in order


class LLMEngine:
    """Continuous-batching inference engine over a paged KV cache.

    ``auto_step=True`` (the serving mode) runs the scheduler on a
    background thread; ``auto_step=False`` lets tests drive ``step()``
    deterministically. Only one thread may step at a time — all scheduler
    and cache state is guarded by one lock.
    """

    def __init__(
        self,
        cfg: EngineConfig | None = None,
        *,
        params: dict | None = None,
        auto_step: bool = True,
        **overrides,
    ):
        if cfg is None:
            cfg = EngineConfig(**overrides)
        elif overrides:
            import dataclasses

            cfg = dataclasses.replace(cfg, **overrides)
        model_cfg = cfg.model_config
        if model_cfg is None:
            if cfg.model == "gpt":
                from ray_tpu.models.gpt import GPTConfig

                model_cfg = GPTConfig.tiny()
            else:
                from ray_tpu.models.llama import LlamaConfig

                model_cfg = LlamaConfig.tiny()
        # thread the decode-attention backend into the (static) model
        # config: EngineConfig wins, then a ModelParallelConfig-style
        # mesh object's knob, else the model config keeps its own
        backend = cfg.attention_backend
        if backend is None:
            backend = getattr(cfg.mesh, "attention_backend", None)
        if backend is None:
            backend = getattr(model_cfg, "attention_backend", "xla")
        # Resolve "auto" to the platform's concrete backend HERE (also
        # validates the knob): the resolved value lands in the frozen
        # model config, so engines that spell the same effective backend
        # differently ("auto" on CPU vs explicit "xla") share one
        # decode.py _jit_cache entry instead of compiling twice.
        from ray_tpu.ops.paged_attention import resolve_backend

        backend = resolve_backend(backend)
        if getattr(model_cfg, "attention_backend", None) != backend:
            import dataclasses

            model_cfg = dataclasses.replace(
                model_cfg, attention_backend=backend
            )
        # thread quantization the same way: EngineConfig wins, else the
        # model config keeps its own. Validated + normalized here so the
        # frozen model config carries the canonical spelling — it is part
        # of the decode.py _jit_cache key, which is exactly what makes a
        # quantized engine its OWN compile-kind set (never mixed traffic
        # with an f32 twin).
        from ray_tpu.ops.quantization import resolve_quantization

        quant = cfg.quantization
        if quant is None:
            quant = getattr(model_cfg, "quantization", None)
        quant = resolve_quantization(quant)
        if getattr(model_cfg, "quantization", None) != quant:
            import dataclasses

            model_cfg = dataclasses.replace(model_cfg, quantization=quant)
        self.cfg = cfg
        self.model_cfg = model_cfg
        n_kv = getattr(model_cfg, "n_kv_head", model_cfg.n_head)
        self.cache = PagedKVCache(
            KVCacheConfig(
                n_layer=model_cfg.n_layer,
                n_kv_head=n_kv,
                head_dim=model_cfg.head_dim,
                num_blocks=cfg.num_blocks,
                block_size=cfg.block_size,
                dtype=model_cfg.dtype,
                host_cache_bytes=cfg.host_cache_bytes,
                quantization=quant,
            )
        )
        # the ModelExecutor seam (executor.py): the engine schedules on
        # host state only; weights, the KV pool arrays, and the jitted
        # step calls live behind the executor — single-device by
        # default, tp/fsdp-sharded when the config names a mesh
        self.executor = build_executor(
            cfg, model_cfg, self.cache, params=params
        )
        # Host-tier demote capture goes through the executor's existing
        # bulk-export funnel (the allowlisted _host_blocks path) — the
        # cache itself never touches the device.
        if cfg.host_cache_bytes > 0:
            self.cache.demote_fn = self.executor.export_blocks
        # speculative decoding: host-side drafter + acceptance accounting
        if cfg.speculative_k < 0:
            raise ValueError("speculative_k must be >= 0")
        if cfg.speculative_k > 0:
            from ray_tpu.serve.llm.drafter import build_drafter

            self._drafter = build_drafter(cfg.drafter)
        else:
            self._drafter = None
        self._spec_steps = 0            # verify steps run
        self._spec_drafted_total = 0    # draft tokens proposed
        self._spec_accepted_total = 0   # draft tokens accepted by verify
        self._spec_committed_total = 0  # tokens emitted by verify steps
        self._batch_buckets = cfg.batch_buckets or pow2_buckets(
            1, cfg.max_batch_size
        )
        self._length_buckets = cfg.length_buckets or pow2_buckets(
            cfg.block_size, model_cfg.max_seq_len
        )
        for b in self._length_buckets:
            if b % cfg.block_size:
                raise ValueError(
                    f"length bucket {b} is not a multiple of "
                    f"block_size={cfg.block_size}"
                )
        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self._waiting: deque[_Request] = deque()
        self._waiting_blocks = 0  # worst-case blocks held by the queue
        self._prefilling: list[_Request] = []  # admitted, prefill incomplete
        self._running: list[_Request] = []
        # ---- priority preemption (ISSUE 17) ----
        if isinstance(cfg.preemption, dict):
            self._preemption: PreemptionConfig | None = PreemptionConfig(
                **cfg.preemption
            )
        else:
            self._preemption = cfg.preemption
        # paused streams: zero KV blocks held, cursor/timeline/FSM intact,
        # token chain re-prefills (host tier serving the hashed full
        # blocks) when pressure clears
        self._preempted: list[_Request] = []
        self._preempted_total = 0
        # True while pressure holds AND no lower-priority victim remains —
        # the point where per-class shedding (autoscaling_policy) kicks in
        self._preempt_exhausted = False
        self._next_id = 0
        self._auto_step = auto_step
        self._thread: threading.Thread | None = None
        self._watchdog: threading.Thread | None = None
        self._stopped = False
        # Set by _fail_engine / the watchdog; read WITHOUT the lock (the
        # whole point is surviving a step that wedged while holding it).
        self._failed: EngineDiedError | None = None
        # perf_counter() at step entry, None when no step is in flight —
        # plain attribute so the watchdog can read it lock-free.
        self._step_begin: float | None = None
        self._rejected_total = 0
        self._cancelled_total = 0
        self._deadline_total = 0
        self._prefill_tokens_total = 0  # tokens actually run through prefill
        # "prefill" | "decode" | None — drives prefill/decode alternation
        # and gives tests a step-order trace.
        self.last_step_kind: str | None = None
        # ---- dispatch-ahead decode pipeline ----
        # the one in-flight decode step (None when the lag is collapsed)
        self._pending: _PendingDecode | None = None
        # Reusable numpy scratch, keyed (name, shape): shapes come from
        # the closed bucket ladders so the pool is bounded. Each key holds
        # TWO buffers used alternately — jnp.asarray can alias host memory
        # zero-copy on the CPU backend, so a buffer must not be mutated
        # until the dispatch that consumed it has provably executed; with
        # the lag-1 sync, the step before last has always synced by the
        # time its buffer comes around again.
        self._scratch: dict[tuple, list] = {}
        self._sync_seconds_total = 0.0
        self._sync_bytes_total = 0
        self._last_sync: dict | None = None  # merged into flight records
        # last cache-stat values already exported to the monotonic counters
        self._exported = {
            "hit": 0, "evict": 0, "cow": 0, "prefill": 0,
            "demote": 0, "promote": 0,
        }
        # ---- observability plane (ISSUE 4) ----
        self._flight = obs.FlightRecorder(cfg.flight_recorder_steps)
        # finished-request timelines, newest-last, bounded
        self._timelines: OrderedDict[Any, dict] = OrderedDict()
        # per-step admission/expiry counts for the flight record (set by
        # step(), read by the phase that runs in the same iteration)
        self._step_admitted = 0
        self._step_expired = 0
        # cache-stat values as of the previous flight record (deltas)
        self._flight_prev = {"cow": 0, "evict": 0, "demote": 0, "promote": 0}
        self._dumped = False  # one post-mortem dump per engine
        # ---- autoscaling signal windows (ISSUE 10) ----
        # Bounded sample/event rings feeding autoscaling_snapshot(): the
        # controller's policy wants recent-tail saturation (queue-wait
        # p95, decode-step p50, miss/reject rates), not lifetime totals.
        self._queue_wait_window: deque[float] = deque(maxlen=256)
        self._decode_step_window: deque[float] = deque(maxlen=256)
        self._reject_clocks: deque[float] = deque(maxlen=512)
        self._deadline_clocks: deque[float] = deque(maxlen=512)
        self._last_snapshot: dict | None = None

        self._m_tokens = metrics.counter(
            "llm_engine_tokens_generated",
            "Tokens generated by the serve/llm engine",
        )
        self._m_queue = metrics.gauge(
            "llm_engine_queue_depth", "Requests waiting for admission"
        )
        self._m_util = metrics.gauge(
            "llm_engine_kv_block_utilization",
            "Fraction of usable KV blocks allocated",
        )
        self._m_latency = metrics.histogram(
            "llm_engine_step_latency_seconds",
            "Engine step latency by kind (prefill/decode)",
            boundaries=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0),
            tag_keys=("kind",),
        )
        self._m_rejected = metrics.counter(
            "llm_requests_rejected",
            "Requests rejected by engine admission control (overload)",
        )
        self._m_cancelled = metrics.counter(
            "llm_requests_cancelled",
            "Requests cancelled (client disconnect / explicit cancel)",
        )
        self._m_deadline = metrics.counter(
            "llm_deadline_exceeded",
            "Requests evicted because deadline_s expired mid-generation",
        )
        self._m_finished = metrics.counter(
            "llm_requests_finished",
            "Requests that completed generation normally (availability "
            "SLO denominator)",
        )
        self._m_hit_tokens = metrics.counter(
            "llm_prefix_hit_tokens",
            "Prompt tokens served from the KV prefix cache (zero compute)",
        )
        self._m_evicted = metrics.counter(
            "llm_prefix_evicted_blocks",
            "Cached KV blocks evicted LRU to satisfy new allocations",
        )
        self._m_cow = metrics.counter(
            "llm_cow_blocks",
            "Copy-on-write block copies (writes into shared KV blocks)",
        )
        self._m_prefill_tokens = metrics.counter(
            "llm_prefill_tokens",
            "Prompt tokens actually computed by prefill (cache misses)",
        )
        self._m_spec_drafted = metrics.counter(
            "llm_spec_drafted_tokens",
            "Draft tokens proposed to speculative verify steps",
        )
        self._m_spec_accepted = metrics.counter(
            "llm_spec_accepted_tokens",
            "Draft tokens accepted by speculative verify steps",
        )
        self._m_spec_committed = metrics.counter(
            "llm_spec_committed_tokens",
            "Tokens committed by speculative verify steps (accepted + "
            "corrected/bonus)",
        )
        self._m_demoted = metrics.counter(
            "llm_kv_demoted_blocks",
            "KV blocks demoted from the device pool into the host cache "
            "tier on LRU eviction",
        )
        self._m_promoted = metrics.counter(
            "llm_kv_promoted_blocks",
            "Host-tier KV blocks promoted back into the device pool on "
            "prefix hits",
        )
        self._m_host_blocks = metrics.gauge(
            "llm_host_cache_blocks",
            "Demoted KV blocks resident in the host cache tier",
        )
        self._m_structured = metrics.counter(
            "llm_structured_requests",
            "Requests admitted with a grammar constraint "
            "(response_format / SamplingParams.structured)",
        )
        self._m_masked_frac = metrics.histogram(
            "llm_structured_masked_fraction",
            "Fraction of the vocab banned by the grammar allow-mask at "
            "each constrained decode position",
            boundaries=(0.5, 0.9, 0.99, 0.995, 0.999, 0.9999),
        )
        self._m_ttft = obs.ttft_histogram()
        self._m_tpot = obs.tpot_histogram()
        self._m_queue_wait = obs.queue_wait_histogram()
        self._m_sync = obs.host_sync_histogram()
        self._m_sync_bytes = obs.sync_bytes_counter()
        self._m_compile = obs.compile_counter()
        self._m_devices = metrics.gauge(
            "llm_executor_devices",
            "Devices driven by this engine's model executor",
        )
        self._m_devices.set(self.executor.num_devices)
        # autoscaling-signal gauges, refreshed on every snapshot pull
        self._m_as_queue = metrics.gauge(
            "llm_queue_depth",
            "Admission queue depth as seen by the autoscaler",
        )
        self._m_as_kv_free = metrics.gauge(
            "llm_kv_free_blocks",
            "Truly free (unallocated, uncached) KV blocks in the pool",
        )
        self._m_as_kv_pressure = metrics.gauge(
            "llm_kv_pool_pressure",
            "Fraction of the usable KV pool a new admission cannot claim "
            "(allocations + reservations + quarantine)",
        )
        # priority preemption (ISSUE 17)
        self._m_preemptions = metrics.counter(
            "llm_preemptions_total",
            "Running streams paused to the host KV tier to make room for "
            "higher-priority work",
        )
        self._m_preempted_streams = metrics.gauge(
            "llm_preempted_streams",
            "Streams currently parked in the preempted state",
        )
        self._m_preempted_wait = metrics.histogram(
            "llm_preempted_wait_seconds",
            "Seconds a preempted stream spent parked before resuming",
            boundaries=(0.01, 0.05, 0.25, 1.0, 5.0, 30.0, 120.0),
        )
        # ---- serving goodput / MFU accounting (ISSUE 13) ----
        # Analytic forward FLOPs per token: 2 FLOPs per weight
        # (multiply+accumulate), the serving-side counterpart of the
        # training 6N rule (docs/ROOFLINE.md, benchmarks/gpt_mfu.py).
        self._flops_per_token = 2.0 * self.executor.num_params
        self._peak_flops = self.executor.peak_tflops * 1e12
        # per step kind: ring of (clock, device_s, tokens) step samples
        # plus the last derived rates, for stats()/the decode bench
        self._goodput_windows: dict[str, deque] = {}
        self._goodput_last: dict[str, dict] = {}
        self._m_goodput = obs.goodput_gauge()
        self._m_mfu = obs.mfu_gauge()
        # count compile events by shape key as DecodeFns sees new
        # signatures (attribute hook, forwarded through the executor —
        # DecodeFns stays constructible bare)
        self.executor.on_new_signature = self._on_new_signature

    # ---------------- public API ----------------

    def submit(
        self,
        prompt: Sequence[int],
        sampling: SamplingParams | None = None,
        *,
        trace_ctx: dict | None = None,
        **sampling_overrides,
    ) -> TokenStream:
        """Enqueue one request; returns a stream of generated token ids.

        ``trace_ctx`` carries the caller's trace context
        (``tracing.current_context()`` shape) across the thread boundary
        into the scheduler; when absent, the submitting thread's active
        span is captured. With a context, the request's phase timeline is
        emitted as ``engine.*`` spans on completion — one trace covers
        HTTP -> router -> replica -> engine.

        Raises ``EngineOverloadedError`` when admission control rejects
        (waiting queue full, or queued worst-case blocks over budget) and
        ``EngineDiedError`` when the engine has already failed.
        """
        if trace_ctx is None:
            trace_ctx = tracing.current_context()
        if sampling is None:
            sampling = SamplingParams(**sampling_overrides)
        elif sampling_overrides:
            import dataclasses

            sampling = dataclasses.replace(sampling, **sampling_overrides)
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must contain at least one token")
        total = len(prompt) + sampling.max_new_tokens
        if total > self.model_cfg.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({sampling.max_new_tokens}) exceeds model max_seq_len "
                f"{self.model_cfg.max_seq_len}"
            )
        need = self.cache.cfg.blocks_for(total)
        if need > self.cache.cfg.usable_blocks:
            raise ValueError(
                f"request needs {need} KV blocks "
                f"but the pool only has {self.cache.cfg.usable_blocks}"
            )
        # grammar constraint: compile (LRU-cached) and position the FSM
        # cursor OUTSIDE the scheduler lock — compile is submit-path
        # work, and a bad grammar is the client's error (GrammarError is
        # a ValueError -> the proxies answer 400, never 500)
        fsm = None
        spec = structured.parse_response_format(sampling.structured)
        if spec is not None:
            dfa = structured.compile_grammar(
                spec, self.model_cfg.vocab_size, self.cfg.eos_id
            )
            fsm = structured.FSMCursor(dfa)
            if sampling.start_index > 0:
                # failover resume: replay the already-delivered tokens
                # (the prompt tail) so the cursor lands where the dead
                # replica's stood
                for t in prompt[-sampling.start_index:]:
                    if not fsm.advance(t):
                        raise structured.GrammarError(
                            f"resumed prefix rejected by the grammar at "
                            f"token {t} (response_format mismatch on "
                            "resume?)"
                        )
            self._m_structured.inc()
        if self._failed is not None:
            raise self._failed
        with self._lock:
            if self._stopped:
                raise RuntimeError("engine is shut down")
            if len(self._waiting) >= self.cfg.max_waiting or (
                self.cfg.max_waiting_blocks is not None
                and self._waiting_blocks + need > self.cfg.max_waiting_blocks
            ):
                self._rejected_total += 1
                self._m_rejected.inc()
                self._reject_clocks.append(obs.clock())
                raise EngineOverloadedError(
                    f"admission queue full ({len(self._waiting)} waiting, "
                    f"{self._waiting_blocks} worst-case blocks queued); "
                    "retry later"
                )
            req = _Request(self._next_id, prompt, sampling, trace_ctx)
            req.fsm = fsm
            self._next_id += 1
            req.submitted_clock = obs.clock()
            self._tl(req, "submitted", prompt_tokens=len(prompt),
                     max_new_tokens=sampling.max_new_tokens)
            self._waiting.append(req)
            self._waiting_blocks += need
            self._m_queue.set(len(self._waiting))
            self._work.notify_all()
        if self._auto_step:
            self._ensure_thread()
        return TokenStream(req)

    def generate(
        self,
        prompt: Sequence[int],
        sampling: SamplingParams | None = None,
        **sampling_overrides,
    ) -> list[int]:
        """Synchronous convenience: submit and collect all tokens."""
        stream = self.submit(prompt, sampling, **sampling_overrides)
        if not self._auto_step:
            while not stream.done:
                if not self.step():
                    break  # pragma: no cover — queue drained early
        return list(stream)

    def step(self) -> bool:
        """One scheduler iteration: expire deadlines, admit what fits,
        then EITHER one prefill chunk (new admissions or the next slice of
        an in-flight prompt) OR one batched decode step. When both kinds
        of work exist the scheduler alternates, so a long chunked prefill
        never starves running sequences of decode steps. Returns False
        when idle."""
        with self._lock:
            self._step_begin = obs.clock()
            try:
                chaos.fire("engine.step")
                self._step_expired = self._expire_deadlines_locked()
                if self._preemption is not None:
                    self._maybe_resume_locked()
                    self._maybe_preempt_locked()
                self._step_admitted = self._admit_locked()
                # Fresh admissions prefill immediately (first token out the
                # door); CONTINUING chunks of a long prompt alternate with
                # decode so running sequences are never starved.
                if self._prefilling and (
                    self.last_step_kind != "prefill"
                    or not self._running
                    or any(not r.started for r in self._prefilling)
                ):
                    self._prefill_chunk_locked()
                    self.last_step_kind = "prefill"
                    return True
                if self._running or self._pending is not None:
                    # pending-but-nothing-running still needs a step: the
                    # lagged tokens must be reconciled (and blocks freed)
                    # even when every row has since finished or evicted
                    self._decode_locked()
                    self.last_step_kind = "decode"
                    return True
                return False
            finally:
                self._step_begin = None

    def cancel(self, request_id) -> bool:
        """Evict a waiting/prefilling/running request, fail its stream
        with ``RequestCancelledError``, and return its KV blocks
        immediately. Returns False when the request is unknown or already
        finished (idempotent — safe to broadcast to every replica)."""
        with self._lock:
            req = self._find_locked(request_id)
            if req is None:
                return False
            self._evict_locked(req)
            self._cancelled_total += 1
            self._m_cancelled.inc()
            self._finish_obs_locked(req, "cancelled")
            req.out.put(
                RequestCancelledError(f"request {request_id!r} cancelled")
            )
            req.out.put(_DONE)
            return True

    # ------------- disaggregated prefill/decode handoff -------------

    def kv_layout(self):
        """The pool's tensor layout as a ``kv_transfer.KVLayout`` — both
        sides of a handoff compare these for exact equality before any
        block moves (a mismatched model/dtype refuses the handoff)."""
        from ray_tpu.serve.llm.kv_transfer import KVLayout

        c = self.cache.cfg
        return KVLayout(
            n_layer=c.n_layer, block_size=c.block_size,
            n_kv_head=c.n_kv_head, head_dim=c.head_dim,
            dtype=self.cache.k.dtype.name,
            quantization=getattr(c, "quantization", None),
        )

    def export_prefix(self, prompt) -> list:
        """PREFILL side of a disaggregated handoff: the resident leading
        full blocks of ``prompt`` as (chain_digest, k_np, v_np) records
        in chain order, host-side. Runs under the scheduler lock so no
        exported block can be LRU-evicted between the chain walk and the
        device gather; a partial chain (earlier eviction) yields a
        shorter — still valid — handoff. Call after prefill finished
        (e.g. a drained max_new_tokens=1 generate), when the prompt's
        blocks are content-addressed in the prefix cache."""
        with self._lock:
            chain = self.cache.export_chain(prompt)
            if not chain:
                return []
            ids = [b for _, b in chain]
            k, v = self.executor.export_blocks(ids)
        return [(d, k[:, i], v[:, i]) for i, (d, _) in enumerate(chain)]

    def adopt_prefix(self, prompt, records) -> int:
        """DECODE side of a handoff: verify each record's chain digest
        against THIS engine's hash of ``prompt`` (kv_cache._block_key —
        the payload's self-declared digests are never trusted), claim
        pool blocks, and land the K/V payloads with one fused scatter.
        Landed blocks enter the prefix cache as cached (refcount-0)
        entries, so the follow-up ``submit`` of the same prompt scores a
        full prefix hit and decodes as if prefilled locally.

        Idempotent and best-effort: already-resident digests are skipped
        (retries, concurrent identical prompts), a digest mismatch or a
        full pool stops the walk — earlier blocks still count. Returns
        the number of leading prompt blocks resident afterwards."""
        bs = self.cache.cfg.block_size
        with self._lock:
            # Cap adoptions at the spare (unreserved) capacity: landing
            # into reserved headroom is wasted motion — the admissions
            # holding those reservations would evict the fresh blocks
            # before the follow-up submit could hit them.
            budget = self.cache.spare_blocks
            digest = b""
            ids: list[int] = []
            ks: list[np.ndarray] = []
            vs: list[np.ndarray] = []
            resident = 0
            for i, (chain, k_blk, v_blk) in enumerate(records):
                if (i + 1) * bs > len(prompt):
                    break  # record beyond the prompt's full blocks
                digest = _block_key(digest, prompt[i * bs:(i + 1) * bs])
                if digest != chain:
                    break  # not our tokens from position 0 — refuse
                if self.cache.has_digest(digest):
                    resident += 1
                    continue
                if len(ids) >= budget:
                    break  # only reserved headroom left — partial is fine
                b = self.cache.adopt_block(digest)
                if b is None:
                    break  # pool has no claimable block — partial is fine
                ids.append(b)
                ks.append(k_blk)
                vs.append(v_blk)
                resident += 1
            if ids:
                from ray_tpu.ops.quantization import stack_blocks

                self.executor.land_blocks(
                    ids, stack_blocks(ks, axis=1), stack_blocks(vs, axis=1)
                )
        return resident

    def stats(self) -> dict:
        with self._lock:
            cs = self.cache.stats
            hit = cs.prefix_hit_tokens
            computed = self._prefill_tokens_total
            return {
                "waiting": len(self._waiting),
                "prefilling": len(self._prefilling),
                "running": len(self._running),
                "preempted": len(self._preempted),
                "preemptions_total": self._preempted_total,
                "preempt_exhausted": self._preempt_exhausted,
                "kv_used_blocks": self.cache.used_blocks,
                "kv_utilization": self.cache.utilization,
                "kv_high_water_blocks": cs.high_water_blocks,
                "num_compiled_shapes": self.fns.num_compiled_shapes,
                "rejected_total": self._rejected_total,
                "cancelled_total": self._cancelled_total,
                "deadline_exceeded_total": self._deadline_total,
                "prefix_hit_tokens": hit,
                "prefix_hit_blocks": cs.prefix_hit_blocks,
                "prefix_cached_blocks": self.cache.cached_blocks,
                "prefix_evicted_blocks": cs.prefix_evicted_blocks,
                "host_cache_blocks": (
                    0 if self.cache.host_tier is None
                    else self.cache.host_tier.blocks
                ),
                "kv_demoted_blocks": cs.demoted_blocks,
                "kv_promoted_blocks": cs.promoted_blocks,
                "cow_blocks": cs.cow_copies,
                "prefill_tokens_total": computed,
                "prefix_hit_rate": hit / max(1, hit + computed),
                "host_sync_seconds_total": round(
                    self._sync_seconds_total, 6
                ),
                "host_sync_bytes_total": self._sync_bytes_total,
                "decode_inflight": 1 if self._pending is not None else 0,
                "spec_steps": self._spec_steps,
                "spec_drafted_tokens": self._spec_drafted_total,
                "spec_accepted_tokens": self._spec_accepted_total,
                "spec_committed_tokens": self._spec_committed_total,
                "spec_accept_rate": (
                    self._spec_accepted_total
                    / max(1, self._spec_drafted_total)
                ),
                "spec_committed_per_step": (
                    self._spec_committed_total / max(1, self._spec_steps)
                ),
                "structured_running": sum(
                    1 for r in self._running if r.fsm is not None
                ),
                "grammar_cache": structured.cache_stats(),
                "goodput": {
                    k: dict(v) for k, v in self._goodput_last.items()
                },
                "executor": self.executor.describe(),
                "failed": self._failed is not None,
            }

    @property
    def fns(self):
        """The executor's DecodeFns (compile-signature accounting) —
        kept as an engine attribute for tests/dashboards that predate
        the executor seam."""
        return self.executor.fns

    @property
    def params(self):
        """Model weights, wherever the executor placed them (one device,
        or sharded over its mesh)."""
        return self.executor.params

    @property
    def num_compiled_shapes(self) -> int:
        return self.fns.num_compiled_shapes

    @property
    def failed(self) -> bool:
        return self._failed is not None

    def request_timeline(self, request_id) -> dict | None:
        """Phase timeline of one request (live or recently finished):
        ``{"request_id", "trace_id", "finish_reason", "events": [...]}``
        where each event is ``{"event", "ts"(wall seconds), ...}`` for
        submitted / admitted / prefill[_chunk] / first_token / token /
        terminal. Finished timelines are kept for the last
        ``timeline_history`` requests; returns None for unknown ids."""
        with self._lock:
            r = self._find_locked(request_id)
            if r is not None:
                return self._timeline_dict(r)
            return self._timelines.get(request_id)

    def autoscaling_snapshot(self) -> dict:
        """Saturation signals for the controller's autoscaling policy
        (serve/autoscaling_policy.py desired_from_signals): queue depth +
        queue-wait p95, KV-pool block accounting collapsed into a single
        pressure fraction, deadline-miss / rejection rates over a trailing
        window, and decode-step p50. All host-side integers/floats — O(1)
        plus a sort of two bounded sample windows — so the controller can
        pull it every reconcile period. Also refreshes the
        ``llm_queue_depth`` / ``llm_kv_free_blocks`` /
        ``llm_kv_pool_pressure`` gauges and records the snapshot in the
        flight ring (``kind="autoscale_snapshot"``)."""
        with self._lock:
            return self._autoscaling_snapshot_locked()

    def _autoscaling_snapshot_locked(self, record: bool = True) -> dict:
        now = obs.clock()
        cache = self.cache
        usable = max(1, cache.cfg.usable_blocks)
        snap = cache.debug_snapshot()
        # Pressure = the fraction of the usable pool a NEW admission
        # cannot claim (see _kv_pressure_locked — the preemption trigger
        # reads the identical number).
        pressure = self._kv_pressure_locked()
        # Two-tier pressure: a pressured device pool backed by a warm
        # host tier is cheaper to miss into than one without (misses
        # promote instead of recomputing), so the host-resident block
        # count discounts the device pressure, bounded at zero. With the
        # tier disabled this equals kv_pool_pressure exactly.
        pressure_two_tier = max(0.0, pressure - snap["host_blocks"] / usable)
        out = {
            "ts_wall": obs.wall(),
            "clock": now,
            "queue_depth": len(self._waiting),
            "queue_wait_p95_s": round(
                _pctile(self._queue_wait_window, 0.95), 6
            ),
            "decode_step_p50_s": round(
                _pctile(self._decode_step_window, 0.50), 6
            ),
            "kv_free_blocks": snap["free_blocks"],
            "kv_cached_blocks": snap["cached_blocks"],
            "kv_quarantined_blocks": snap["quarantined_blocks"],
            "kv_pool_pressure": round(pressure, 4),
            "kv_host_cached_blocks": snap["host_blocks"],
            "kv_host_cache_bytes": snap["host_bytes"],
            "kv_pressure_two_tier": round(pressure_two_tier, 4),
            # Prefix-routing piggyback: the bounded digest summary rides
            # the snapshot the controller already polls, plus the two
            # constants the router needs to hash raw prompts into the
            # same chain-digest space (encode_text is ``byte % vocab``).
            "prefix_digests": cache.prefix_digest_summary(),
            "block_size": cache.cfg.block_size,
            "vocab_size": self.model_cfg.vocab_size,
            "deadline_miss_rate": round(
                _window_rate(self._deadline_clocks, now), 4
            ),
            "rejection_rate": round(
                _window_rate(self._reject_clocks, now), 4
            ),
            "running": len(self._running),
            "prefilling": len(self._prefilling),
            # per-class queue depth + preemption saturation: the
            # controller's class-aware shed policy
            # (autoscaling_policy.shed_classes) degrades batch traffic
            # first, and only once preemption itself is exhausted
            "queue_depth_by_class": {
                p: sum(
                    1 for r in self._waiting if r.sampling.priority == p
                )
                for p in _PRIORITIES
            },
            "preempted_streams": len(self._preempted),
            "preempt_exhausted": self._preempt_exhausted,
            "failed": self._failed is not None,
        }
        self._m_as_queue.set(out["queue_depth"])
        self._m_as_kv_free.set(out["kv_free_blocks"])
        self._m_as_kv_pressure.set(out["kv_pool_pressure"])
        self._last_snapshot = out
        if record:  # debug_dump() observes without touching the ring
            self._flight.record(dict(out, kind="autoscale_snapshot",
                                     ts=out["ts_wall"]))
        return out

    def debug_dump(self) -> dict:
        """One-call post-mortem/state dump: flight-recorder ring, engine
        stats, cache snapshot, the latest autoscaling snapshot, compiled
        shapes, and the process's event_stats. Exposed replica-side as
        ``LLMDeployment.debug_dump`` and proxy-side as
        ``GET /debug/llm``."""
        with self._lock:
            return self._flight.dump("debug", extra={
                "stats": self.stats(),
                "executor": self.executor.describe(),
                "cache": self.cache.debug_snapshot(),
                "autoscaling_snapshot": self._autoscaling_snapshot_locked(
                    record=False),
                "compiled_shapes": sorted(
                    obs.shape_key(s) for s in self.fns.signatures
                ),
                "archived_timelines": len(self._timelines),
                # traced requests currently live in the engine, so an
                # operator staring at a wedged dump can jump straight to
                # the matching fleet traces (/api/traces/<id>)
                "live_trace_ids": self._trace_ids_locked(
                    list(self._waiting) + self._prefilling
                    + self._running + self._preempted),
            })

    def shutdown(self, dump: bool | str | None = None) -> None:
        """Stop stepping, fail every pending stream with a clear error,
        and return ALL KV blocks (allocations, reservations, and the
        prefix cache) to the pool — repeated create/shutdown in one
        process is leak-free.

        ``dump=True`` writes a flight-recorder JSON dump to the configured
        dump dir on the way out; a string is an explicit file path."""
        with self._lock:
            if self._stopped:
                return
            if dump:
                self._dump("shutdown",
                           path=dump if isinstance(dump, str) else None)
            self._stopped = True
            err = RequestCancelledError("engine shut down")
            for r in (list(self._waiting) + self._prefilling
                      + self._running + self._preempted):
                if not r.done:
                    r.done = True
                    self._finish_obs_locked(r, "shutdown")
                    r.out.put(err)
                    r.out.put(_DONE)
            self._pending = None
            self.cache.release_all()
            self._waiting.clear()
            self._waiting_blocks = 0
            self._prefilling.clear()
            self._running.clear()
            self._preempted.clear()
            self._m_preempted_streams.set(0)
            self._m_queue.set(0)
            self._m_util.set(self.cache.utilization)
            self._work.notify_all()
        for t in (self._thread, self._watchdog):
            if t is not None:
                t.join(timeout=5)
        self._thread = None
        self._watchdog = None

    # ---------------- scheduler internals (lock held) ----------------

    def _find_locked(self, request_id) -> _Request | None:
        for r in self._running:
            if r.id == request_id:
                return r
        for r in self._prefilling:
            if r.id == request_id:
                return r
        for r in self._waiting:
            if r.id == request_id:
                return r
        for r in self._preempted:
            if r.id == request_id:
                return r
        return None

    def _release_blocks_locked(self, r: _Request) -> None:
        """Return an admitted request's blocks (allocation + leftover
        reservation) to the pool EXACTLY ONCE, respecting the dispatch
        lag: while the row still has an in-flight speculative step
        (``inflight > 0``) release is deferred to the reconcile that
        retires it, and blocks freed while any other dispatch is in
        flight are quarantined until the next sync proves the dispatch
        executed (kv_cache.free/flush_quarantine)."""
        if r.blocks_released or r.inflight > 0:
            return
        r.blocks_released = True
        leftover = r.reserved_blocks - r.drawn_blocks
        self.cache.free(r.id, quarantine=self._pending is not None)
        if leftover > 0:
            self.cache.release_reservation(leftover)
        self._work.notify_all()  # freed blocks may unblock admissions

    def _evict_locked(self, r: _Request) -> None:
        """Remove a live request from the scheduler and return its blocks
        (allocation + leftover reservation for admitted; queued worst-case
        budget for waiting). Does NOT touch the output stream."""
        if r in self._running or r in self._prefilling:
            if r in self._running:
                self._running.remove(r)
            else:
                self._prefilling.remove(r)
            r.done = True  # before release: an inflight row defers it
            self._release_blocks_locked(r)
        elif r in self._preempted:
            # parked streams hold ZERO blocks (released at preemption) —
            # unparking is the whole eviction; the demoted chain stays
            # behind as an ordinary cache entry
            self._preempted.remove(r)
            self._m_preempted_streams.set(len(self._preempted))
        else:
            try:
                self._waiting.remove(r)
            except ValueError:  # pragma: no cover — already gone
                pass
            else:
                self._waiting_blocks -= self.cache.cfg.blocks_for(
                    len(r.prompt) + r.sampling.max_new_tokens
                )
        r.done = True
        self._m_queue.set(len(self._waiting))
        self._m_util.set(self.cache.utilization)
        self._work.notify_all()  # freed blocks may unblock admissions

    def _expire_deadlines_locked(self) -> int:
        now = time.monotonic()
        expired = 0
        for r in [
            r
            for r in list(self._waiting) + self._prefilling + self._running
            + self._preempted
            if r.deadline is not None and now >= r.deadline
        ]:
            self._evict_locked(r)
            self._deadline_total += 1
            self._m_deadline.inc()
            self._deadline_clocks.append(obs.clock())
            expired += 1
            self._finish_obs_locked(r, "expired")
            r.out.put(
                DeadlineExceededError(
                    f"request {r.id!r} deadline "
                    f"({r.sampling.deadline_s}s) expired after "
                    f"{len(r.generated)} tokens"
                )
            )
            r.out.put(_DONE)
        return expired

    # ---------------- priority preemption (ISSUE 17) ----------------

    def _kv_pressure_locked(self) -> float:
        """Fraction of the usable KV pool a new admission cannot claim:
        live allocations, reservations, and quarantined blocks count
        against it; LRU-cached prefix blocks do not (evictable on
        demand). The same definition ``autoscaling_snapshot`` exports as
        ``kv_pool_pressure`` — preemption triggers and the autoscaler
        read one number."""
        cache = self.cache
        usable = max(1, cache.cfg.usable_blocks)
        claimable = max(0, cache.available_blocks - cache.reserved_blocks)
        return min(1.0, max(0.0, 1.0 - claimable / usable))

    def _rank_locked(self, r: _Request, now: float) -> int:
        """Effective priority rank of a request at ``now`` (obs.clock):
        the class rank (batch < default < interactive), boosted ABOVE
        interactive once the request has waited or sat parked past the
        starvation-aging floor. The boost is double-duty: an aged waiter
        outranks every class for admission ordering, and an aged (or
        once-parked-long-enough) running stream stops being preemptible —
        together they guarantee batch traffic always finishes."""
        pc = self._preemption
        rank = PRIORITY_RANK[r.sampling.priority]
        ref = (r.preempted_clock if r.preempted_clock is not None
               else r.submitted_clock)
        if pc is not None and ref is not None and now - ref >= pc.aging_s:
            rank = len(_PRIORITIES)  # aged past every class
        return rank

    def _maybe_preempt_locked(self) -> None:
        """Pause the lowest-priority RUNNING stream when KV-pool pressure
        or a higher-priority waiter's queue age crosses the
        PreemptionConfig thresholds. ONE victim per step: a preemption
        frees a whole chain at once, and admission runs right after in
        the same iteration, so pausing more per step would overshoot
        before the freed headroom is even observed. While pressure holds
        but no victim outranked by a waiter remains (or the parked set is
        at its cap), ``_preempt_exhausted`` latches True — the signal
        per-class shedding (autoscaling_policy.shed_classes) keys on."""
        pc = self._preemption
        if not self._waiting:
            self._preempt_exhausted = False
            return
        now = obs.clock()
        waiter = max(
            self._waiting, key=lambda rq: self._rank_locked(rq, now)
        )
        w_rank = self._rank_locked(waiter, now)
        pressured = (
            self._kv_pressure_locked() >= pc.kv_pressure
            or now - waiter.submitted_clock >= pc.queue_wait_s
        )
        if not pressured:
            self._preempt_exhausted = False
            return
        victims = [
            r for r in self._running
            if self._rank_locked(r, now) < w_rank
        ]
        if not victims or len(self._preempted) >= pc.max_preempted:
            self._preempt_exhausted = True
            return
        self._preempt_exhausted = False
        # lowest class first; within a class the YOUNGEST stream pauses
        # (oldest streams are closest to completion — finishing them
        # releases their blocks for good)
        victim = min(
            victims,
            key=lambda r: (self._rank_locked(r, now),
                           -(r.submitted_clock or 0.0)),
        )
        self._preempt_one_locked(victim, now)

    def _preempt_one_locked(self, r: _Request, now: float) -> bool:
        """Pause one running stream: collapse the dispatch lag so nothing
        in flight references its rows, content-address its resident
        blocks in the prefix cache and demote them into the host tier
        (insurance against device LRU eviction while parked), release
        its allocation + leftover reservation exactly once, and park it
        in the ``preempted`` state with cursor/timeline/FSM intact. On
        resume the chain re-prefills — prefix hits serve the registered
        blocks from the device LRU or promote them back through the
        batched ``land_blocks`` scatter — and keyed (seed, position)
        sampling reproduces the remaining tokens byte-identically."""
        chaos.fire("llm.preempt", request=r.id,
                   priority=r.sampling.priority)
        if self._pending is not None:
            # the victim (or a neighbor) may be in the dispatched step:
            # reconcile first so its inflight count is 0 and the free
            # below needs no quarantine. The victim may COMPLETE here —
            # its lagged token was its last — in which case there is
            # nothing left to pause.
            self._reconcile_locked(self._pending)
        if r.done or r not in self._running:
            return False
        chain = list(r.prompt) + list(r.generated)
        # resident KV covers [0, total_len - 1): the last emitted
        # token's K/V lands only when it is fed as the next decode input
        resident = r.total_len - 1
        if self.cfg.prefix_caching:
            self.cache.register_prefix(r.id, chain, resident)
        demoted = self.cache.demote_chain(chain, resident,
                                          trace_ctx=r.trace_ctx)
        self._running.remove(r)
        self._release_blocks_locked(r)
        # back to the pre-admission shape (the resume is a plain
        # re-admission of prompt + generated via pending_resume)
        r.blocks_released = False
        r.reserved_blocks = 0
        r.drawn_blocks = 0
        r.prefill_done = 0
        r.cached_tokens = 0
        r.started = False
        r.skips = 0
        r.table_np = None
        r.table_key = None
        r.pending_resume = chain
        r.preempted_clock = now
        r.preempt_count += 1
        self._preempted.append(r)
        self._preempted_total += 1
        self._m_preemptions.inc()
        self._m_preempted_streams.set(len(self._preempted))
        self._m_util.set(self.cache.utilization)
        self._tl(r, "preempted", generated=len(r.generated),
                 priority=r.sampling.priority, demoted_blocks=demoted)
        return True

    def _maybe_resume_locked(self) -> None:
        """Re-admit parked streams once pressure clears below
        ``resume_pressure`` — or unconditionally once a stream's aging
        floor trips (the starvation guarantee). Highest effective rank
        first, oldest park first within a class; stops at the first
        candidate that doesn't fit so resumes stay ordered. A resume is
        a normal re-admission of the full token chain; the final prefill
        chunk re-samples the next token at its true absolute position,
        so the joined stream is byte-identical to an unpaused run."""
        pc = self._preemption
        if not self._preempted:
            return
        now = obs.clock()
        while self._preempted:
            cand = max(
                self._preempted,
                key=lambda r: (self._rank_locked(r, now),
                               -(r.preempted_clock or 0.0)),
            )
            aged = now - cand.preempted_clock >= pc.aging_s
            if not aged and self._kv_pressure_locked() > pc.resume_pressure:
                break
            if (len(self._running) + len(self._prefilling)
                    >= self.cfg.max_batch_size):
                break
            if not self._try_admit_one_locked(cand):
                break
            self._preempted.remove(cand)
            self._prefilling.append(cand)
            parked = now - cand.preempted_clock
            self._m_preempted_wait.observe(parked)
            self._m_preempted_streams.set(len(self._preempted))
            chaos.fire("llm.resume_preempted", request=cand.id,
                       parked_s=parked)
            self._tl(cand, "resumed",
                     parked_ms=round(parked * 1000.0, 3),
                     cached_tokens=cand.cached_tokens)
            # preempted_clock deliberately stays set: the resumed stream
            # keeps aging from its park time, so a stream that has
            # already been paused once soon becomes non-preemptible
            # (anti-thrash) via the _rank_locked boost

    def _try_admit_one_locked(self, req: _Request) -> bool:
        """Reserve worst-case blocks for one request, allocate its table,
        and map its resident prompt prefix. Returns False (no state
        change) when the reservation doesn't fit right now.

        Reservation sizing: ``blocks_for(prompt + max_new_tokens)``, plus
        ONE extra block when the ENTIRE prompt is resident — the last
        prompt token must still be recomputed to produce first-token
        logits, and that write lands in a shared hashed block, so it
        always triggers exactly one copy-on-write copy."""
        bs = self.cfg.block_size
        # Resumed-from-preemption rows prefill prompt + generated-so-far,
        # but the worst case is unchanged: len(toks) + tokens-still-to-
        # generate == len(prompt) + max_new_tokens, always.
        toks = req.prefill_tokens
        total = len(toks) + (req.sampling.max_new_tokens - len(req.generated))
        need = self.cache.cfg.blocks_for(total)
        max_hit_blocks = None
        if self.cfg.prefix_caching:
            hit_blocks = self.cache.peek_prefix(toks)
            if hit_blocks * bs >= len(toks):  # full-chain hit
                if (
                    need + 1 <= self.cache.cfg.usable_blocks
                    and self.cache.can_reserve(need + 1)
                ):
                    need += 1
                    max_hit_blocks = hit_blocks
                elif self.cache.can_reserve(need):
                    # no headroom for the COW copy: drop the last hit
                    # block and recompute it instead
                    max_hit_blocks = hit_blocks - 1
                else:
                    return False
            else:
                if not self.cache.can_reserve(need):
                    return False
                max_hit_blocks = hit_blocks
        elif not self.cache.can_reserve(need):
            return False
        self.cache.reserve(need)
        req.reserved_blocks = need
        self.cache.allocate(req.id)
        if self.cfg.prefix_caching:
            promoted0 = self.cache.stats.promoted_blocks
            hit_tokens = self.cache.assign_prefix(
                req.id, toks, max_blocks=max_hit_blocks
            )
            req.drawn_blocks += hit_tokens // bs
            # a full-chain hit still recomputes the LAST token (a 1-token
            # chunk) so the engine has logits to sample from
            req.prefill_done = min(hit_tokens, len(toks) - 1)
            req.cached_tokens = req.prefill_done
            if req.trace_ctx:
                # host->device promotions staged for THIS admission show
                # up on the request's trace (span rendered at finish)
                promoted = self.cache.stats.promoted_blocks - promoted0
                if promoted:
                    self._tl(req, "kv_promote", blocks=promoted,
                             hit_tokens=hit_tokens)
        return True

    def _admit_locked(self) -> int:
        """Move waiting requests into the prefilling set. FIFO first; when
        the head's reservation doesn't fit, probe up to
        ``admission_probe`` requests behind it — unless the head has
        already been skipped ``admission_max_skips`` times, in which case
        admission stalls until the head fits (no starvation). With
        preemption enabled, candidates are ordered by effective priority
        rank first (stable sort — FIFO within a class, and the starvation-
        aging boost floats a starved request above interactive). Returns
        the number admitted this step."""
        admitted = 0
        if not self._waiting:
            return 0
        if self._preemption is not None and len(self._waiting) > 1:
            now = obs.clock()
            order = sorted(
                self._waiting, key=lambda rq: -self._rank_locked(rq, now)
            )
        else:
            order = list(self._waiting)
        head = order[0]
        probe_budget = (
            self.cfg.admission_probe
            if head.skips < self.cfg.admission_max_skips
            else 0
        )
        probed = 0
        idx = 0
        while (
            idx < len(order)
            and len(self._running) + len(self._prefilling)
            < self.cfg.max_batch_size
            and admitted < self.cfg.max_prefill_batch
        ):
            req = order[idx]
            if self._try_admit_one_locked(req):
                self._waiting.remove(req)
                self._waiting_blocks -= self.cache.cfg.blocks_for(
                    len(req.prompt) + req.sampling.max_new_tokens
                )
                self._prefilling.append(req)
                admitted += 1
                idx += 1
                wait = obs.clock() - req.submitted_clock
                self._m_queue_wait.observe(wait)
                self._queue_wait_window.append(wait)
                self._tl(req, "admitted",
                         cached_tokens=req.cached_tokens,
                         reserved_blocks=req.reserved_blocks)
            else:
                if probed >= probe_budget:
                    break
                probed += 1
                idx += 1
        if admitted:
            if head in self._waiting:
                head.skips += 1  # someone was admitted past the head
            self._m_queue.set(len(self._waiting))
        return admitted

    def _table_for(self, r: _Request, nb: int) -> np.ndarray:
        """Host block table for one request, rebuilt only when a block was
        appended/replaced (version bump) or the padded width changed."""
        key = (nb, self.cache.table_version(r.id))
        if r.table_key != key:
            r.table_np = self.cache.block_table(r.id, nb)
            r.table_key = key
        return r.table_np

    def _apply_copies_locked(self, pairs: list[tuple[int, int]]) -> None:
        """Clone shared blocks on device (COW) before a write lands —
        pow2 pair-list padding and the fused on-device copy live in the
        executor (executor.copy_blocks)."""
        if not pairs:
            return
        self.executor.copy_blocks(pairs)

    def _apply_promotions_locked(self) -> None:
        """Land host-tier promotions staged by admission as ONE fused
        ``land_blocks`` scatter (the handoff-landing path — host->device
        only, no new sync point, no new compile kind). Must run at the
        TOP of a dispatch window, before ``prepare_write``/
        ``_apply_copies_locked``: a COW fork of a promoted block must
        clone landed content, and a capacity eviction in the same window
        must see the landing acked before it may demote-export."""
        staged = self.cache.take_pending_promotions()
        if not staged:
            return
        chaos.fire("llm.kv.promote", blocks=len(staged))
        from ray_tpu.ops.quantization import stack_blocks

        ids = [b for b, _, _ in staged]
        self.executor.land_blocks(
            ids,
            stack_blocks([k for _, k, _ in staged], axis=1),
            stack_blocks([v for _, _, v in staged], axis=1),
        )
        self.cache.promotions_landed(ids)

    def _prefill_chunk_locked(self) -> None:
        """Run ONE prefill call for up to ``max_prefill_batch`` admitted
        requests: each contributes its next chunk (the whole uncached
        suffix when ``prefill_chunk_tokens`` is None). Cold whole prompts
        take the monolithic reference path (start=None) — identical
        numerics and compile signatures to PR 1; anything mid-prompt or
        prefix-seeded takes the paged chunk path at true positions."""
        batch = self._prefilling[: self.cfg.max_prefill_batch]
        chaos.fire("engine.prefill", batch=len(batch))
        t0 = obs.clock()
        t0_wall = obs.wall()
        # staged host-tier promotions land before capacity/COW work so a
        # same-window eviction or fork of a promoted block is safe
        self._apply_promotions_locked()
        bs = self.cfg.block_size
        cap = self.cfg.prefill_chunk_tokens
        ns = []
        for r in batch:
            r.started = True
            remaining = len(r.prefill_tokens) - r.prefill_done
            ns.append(remaining if cap is None else min(remaining, cap))
        pairs: list[tuple[int, int]] = []
        for r, n in zip(batch, ns):
            appended = self.cache.ensure_capacity(r.id, r.prefill_done + n)
            r.drawn_blocks += appended
            cow = self.cache.prepare_write(
                r.id, r.prefill_done, r.prefill_done + n
            )
            r.drawn_blocks += len(cow)
            pairs.extend(cow)
        self._apply_copies_locked(pairs)

        legacy = all(
            r.prefill_done == 0 and n == len(r.prefill_tokens)
            for r, n in zip(batch, ns)
        )
        S = pad_to_bucket(max(ns), self._length_buckets)
        B = pad_to_bucket(len(batch), self._batch_buckets)
        if legacy:
            nb = S // bs
        else:
            ctx = pad_to_bucket(
                max(r.prefill_done + n for r, n in zip(batch, ns)),
                self._length_buckets,
            )
            nb = ctx // bs
        tokens = self._scratch_buf("pf_tokens", (B, S), np.int32)
        lengths = self._scratch_buf("pf_lengths", (B,), np.int32)
        starts = self._scratch_buf("pf_starts", (B,), np.int32)
        tables = self._scratch_buf("pf_tables", (B, nb), np.int32)
        # reused buffers: stale padding rows/columns must be re-zeroed
        # (a stale table row could point at blocks now owned by a LIVE
        # sequence — padding writes must stay on the garbage block)
        tokens[len(batch):] = 0
        lengths[:] = 1  # padding rows: length 1
        starts[len(batch):] = 0
        tables[len(batch):] = 0
        for i, (r, n) in enumerate(zip(batch, ns)):
            toks = r.prefill_tokens
            tokens[i, :n] = toks[r.prefill_done : r.prefill_done + n]
            tokens[i, n:] = 0
            lengths[i] = n
            starts[i] = r.prefill_done
            tables[i] = self._table_for(r, nb)
        sample = self._sample_args_locked(batch, B)
        if legacy:
            toks_dev = self.executor.prefill(
                tokens, lengths, tables, sample=sample
            )
        else:
            toks_dev = self.executor.prefill_chunk(
                tokens, lengths, starts, tables, sample=sample
            )
        # first tokens sync immediately (lag 0): TTFT must not wait for
        # the next decode step, and only final-chunk rows emit anyway
        host = self._sync_tokens_locked(toks_dev, lag=0)
        # dt covers the phase's real cost — COW copies, padding, the
        # jitted call and THE host sync. The same value feeds the latency
        # histogram, the flight record, event_stats, and the per-request
        # chunk timeline entries, so every record agrees (one clock).
        dt = obs.clock() - t0
        kind = "prefill" if legacy else "prefill_chunk"
        for i, (r, n) in enumerate(zip(batch, ns)):
            toks = r.prefill_tokens
            r.prefill_done += n
            self._prefill_tokens_total += n
            self._tl(r, kind, ts=t0_wall, dur_ms=round(dt * 1000.0, 3),
                     tokens=n, prefill_done=r.prefill_done)
            if self.cfg.prefix_caching:
                self.cache.register_prefix(r.id, toks, r.prefill_done)
            if r.prefill_done >= len(toks):
                self._prefilling.remove(r)
                # resume-from-preemption chains are fully resident again:
                # from here the row decodes exactly like an unpaused one
                r.pending_resume = None
                # the model samples from last-VALID-token logits per row —
                # for the final chunk that is the last prompt token (or,
                # resuming, the last already-emitted token: the keyed
                # sampler reproduces the next token byte-identically)
                self._emit_token_locked(r, int(host[i]))
                if not r.done:
                    self._running.append(r)
        self._m_util.set(self.cache.utilization)
        self._sync_cache_counters_locked()
        self._m_latency.observe(dt, tags={"kind": kind})
        self._goodput_record_locked(kind, dt, int(sum(ns)))
        event_stats.record(f"llm.engine.step.{kind}", dt)
        self._flight_record_locked(
            kind, t0_wall, dt, batch=len(batch), bucket_b=B, bucket_len=S,
            nb=nb, tokens=int(sum(ns)),
            trace_ids=self._trace_ids_locked(batch),
        )

    def _decode_locked(self) -> None:
        """One pipelined decode iteration (the tentpole's dispatch-ahead
        loop). Steady state — the eligible batch is exactly the batch of
        the in-flight step — dispatches step N+1 feeding straight from
        step N's on-device sampled-token array, THEN syncs step N's ids:
        all the host-side work above the dispatch (bucketing, COW prep,
        table/position packing) overlaps step N's device compute, and the
        sync itself is near-free because step N already finished. Any
        batch change (join, finish, eviction, a row hitting its token
        budget) first collapses the lag: reconcile the pending step on
        host state, rebuild the batch, and dispatch fresh from host
        tokens."""
        chaos.fire("engine.decode", batch=len(self._running))
        t0 = obs.clock()
        t0_wall = obs.wall()
        bs = self.cfg.block_size
        pending = self._pending

        def eligible() -> list[_Request]:
            # budget counts the speculative in-flight token too — a row
            # at max_new_tokens-1 with one token in flight must not be
            # dispatched again (its last token arrives at reconcile)
            return [
                r for r in self._running
                if len(r.generated) + r.inflight < r.sampling.max_new_tokens
            ]

        batch = eligible()
        emitted = 0
        # ---- speculative draft-and-verify (cfg.speculative_k > 0) ----
        # Drafting needs the rows' COMMITTED tokens on host, so a verify
        # step can never be dispatched ahead: when any row has drafts,
        # collapse the lag-1 pending first, re-draft on the reconciled
        # state, and run ONE synchronous verify step committing 1..k+1
        # tokens per row. When no row drafts anything, fall through to
        # the plain pipelined decode below — drafter-hostile traffic
        # keeps the lag-1 dispatch-ahead path untouched.
        if self._drafter is not None and batch:
            proposals = self._propose_drafts_locked(batch)
            if proposals is not None:
                if pending is not None:
                    emitted += self._reconcile_locked(pending)
                    pending = None
                    batch = eligible()
                    proposals = (
                        self._propose_drafts_locked(batch) if batch else None
                    )
                if batch and proposals is not None:
                    self._verify_locked(batch, proposals, t0, t0_wall,
                                        emitted)
                    return
        # list equality is element identity here: same _Request objects
        # in the same order <=> nothing joined/finished/evicted.
        # Grammar-constrained rows force the lag to collapse every step:
        # the allow-mask staged for step N+1 is a function of the FSM
        # state AFTER step N's token, which only exists host-side once
        # N's ids are synced — so reconcile first, then dispatch (lag-0
        # for constrained batches, the dispatch-ahead win preserved for
        # everything else).
        constrained = any(r.fsm is not None for r in batch)
        steady = (
            pending is not None and batch == pending.batch
            and not constrained
        )
        if pending is not None and not steady:
            emitted += self._reconcile_locked(pending)
            pending = None
            batch = eligible()
        if not batch:
            # pure drain step: the reconcile above retired the last
            # in-flight tokens; record it so the flight ring shows the
            # lag collapsing rather than a mystery gap
            dt = obs.clock() - t0
            self._m_util.set(self.cache.utilization)
            self._sync_cache_counters_locked()
            self._m_latency.observe(dt, tags={"kind": "decode"})
            self._goodput_record_locked("decode", dt, emitted)
            event_stats.record("llm.engine.step.decode", dt)
            self._flight_record_locked(
                "decode", t0_wall, dt, batch=0, tokens=emitted,
            )
            return
        self._apply_promotions_locked()
        pairs: list[tuple[int, int]] = []
        for r in batch:
            # effective length includes the in-flight token: its K/V row
            # lands at position eff-1 during this dispatch
            eff = r.total_len + r.inflight
            appended = self.cache.ensure_capacity(r.id, eff)
            r.drawn_blocks += appended
            cow = self.cache.prepare_write(r.id, eff - 1, eff)
            r.drawn_blocks += len(cow)
            pairs.extend(cow)
        self._apply_copies_locked(pairs)
        B = pad_to_bucket(len(batch), self._batch_buckets)
        # a row can HOLD blocks past its committed frontier (a verify
        # step whose drafts were rejected appended them; they're reused
        # as the frontier advances) — the table must span what's held,
        # not just what's committed
        ctx = pad_to_bucket(
            max(
                max(r.total_len + r.inflight,
                    self.cache.num_allocated(r.id) * bs)
                for r in batch
            ),
            self._length_buckets,
        )
        nb = ctx // bs
        positions = self._scratch_buf("dec_positions", (B,), np.int32)
        tables = self._scratch_buf("dec_tables", (B, nb), np.int32)
        # reused buffers: re-zero padding rows (a stale table row could
        # point at blocks now owned by a live sequence)
        positions[len(batch):] = 0
        tables[len(batch):] = 0
        for i, r in enumerate(batch):
            positions[i] = r.total_len + r.inflight - 1
            tables[i] = self._table_for(r, nb)
        if steady:
            # feed step N+1 from step N's sampled ids without a host
            # round-trip — THE datapath that makes the pipeline a win
            # (the executor passes on-device arrays through untouched)
            tokens_src = pending.tokens
        else:
            tokens = self._scratch_buf("dec_tokens", (B,), np.int32)
            tokens[len(batch):] = 0
            for i, r in enumerate(batch):
                tokens[i] = r.generated[-1] if r.generated else r.prompt[-1]
            tokens_src = tokens
        next_dev = self.executor.decode_step(
            tokens_src, positions, tables,
            sample=self._sample_args_locked(batch, B),
        )
        for r in batch:
            r.inflight += 1
        self._pending = _PendingDecode(tokens=next_dev, batch=batch)
        if steady:
            # reconcile step N only after dispatching N+1 — the host work
            # above ran while N was still executing on device
            emitted += self._reconcile_locked(pending)
        dt = obs.clock() - t0
        self._m_util.set(self.cache.utilization)
        self._sync_cache_counters_locked()
        self._m_latency.observe(dt, tags={"kind": "decode"})
        self._goodput_record_locked("decode", dt, emitted)
        self._decode_step_window.append(dt)
        event_stats.record("llm.engine.step.decode", dt)
        self._flight_record_locked(
            "decode", t0_wall, dt, batch=len(batch), bucket_b=B,
            bucket_len=ctx, nb=nb, tokens=emitted,
            trace_ids=self._trace_ids_locked(batch),
        )

    def _reconcile_locked(self, pending: _PendingDecode) -> int:
        """Collapse the dispatch lag for one in-flight decode step: sync
        its sampled ids (THE O(batch) int32 transfer), flush the block
        quarantine (a completed sync proves every earlier dispatch
        executed, so blocks freed before this step's dispatch are safe to
        reuse), then emit/retire per row. Rows that terminated after the
        dispatch (EOS raced the lag, cancel, deadline, failover) drop
        their speculative token here and release their blocks — exactly
        once, via the inflight-guarded release. Returns tokens emitted."""
        if self._pending is pending:
            self._pending = None
        toks = self._sync_tokens_locked(pending.tokens, lag=1)
        self.cache.flush_quarantine()
        emitted = 0
        for i, r in enumerate(pending.batch):
            r.inflight -= 1
            if r.done:
                # the <=1 wasted speculative row per finished request
                self._release_blocks_locked(r)
                continue
            self._emit_token_locked(r, int(toks[i]))
            emitted += 1
        self._running = [r for r in self._running if not r.done]
        return emitted

    def _propose_drafts_locked(self, batch: list) -> list[list[int]] | None:
        """Ask the drafter for up to ``speculative_k`` candidate tokens
        per row. Per-row draft length is clamped to the row's remaining
        token budget minus one — so committed tokens (accepted prefix +
        one corrected/bonus) can never exceed ``max_new_tokens``, which
        also keeps every speculative KV write inside the row's worst-case
        block reservation. Out-of-vocab proposals truncate the draft (a
        drafter is a performance hint, never a correctness input).
        Returns None when no row drafted anything."""
        k = self.cfg.speculative_k
        V = self.model_cfg.vocab_size
        out: list[list[int]] = []
        any_draft = False
        for r in batch:
            k_eff = min(
                k,
                r.sampling.max_new_tokens - len(r.generated)
                - r.inflight - 1,
            )
            clean: list[int] = []
            if k_eff > 0:
                for t in self._drafter.propose(
                    r.prompt, r.generated, k_eff
                ):
                    t = int(t)
                    if not 0 <= t < V or len(clean) >= k_eff:
                        break
                    clean.append(t)
                if r.fsm is not None and clean:
                    # constrained rows: only a grammar-valid prefix can
                    # ever be accepted, so truncate at the first token
                    # the DFA rejects — verify stays lossless, and an
                    # empty draft is the per-request spec-off fallback
                    # (that row degenerates to a 1-token verify)
                    clean = r.fsm.filter_draft(clean)
            out.append(clean)
            any_draft = any_draft or bool(clean)
        return out if any_draft else None

    def _verify_locked(self, batch: list, proposals: list[list[int]],
                       t0: float, t0_wall: float, emitted: int) -> None:
        """One synchronous speculative verify step over ``batch``: stage
        the [B, W] window (column 0 = each row's last committed token —
        exactly what a plain decode step would feed — then its drafts;
        W = speculative_k + 1 FROZEN per engine so the signature set
        stays closed under mixed traffic), run the jitted verify, sync
        the packed [B, W+1] verdicts (lag 0 — the next window's drafts
        need these tokens on host), and emit 1..draft_len+1 committed
        tokens per row. EOS landing mid-window stops that row's emission
        on the spot; the remaining verdicts are dead and its blocks
        release exactly once through the normal completion path
        (``inflight`` is 0 here — verify never runs under the lag)."""
        bs = self.cfg.block_size
        W = self.cfg.speculative_k + 1
        draft_lens = [len(p) for p in proposals]
        self._apply_promotions_locked()
        pairs: list[tuple[int, int]] = []
        for r, dl in zip(batch, draft_lens):
            # the window writes K/V at positions total_len-1 ..
            # total_len-1+dl (committed column + live draft columns;
            # padding columns redirect to the garbage block, so the
            # reservation only covers the clamped draft length)
            eff = r.total_len + dl
            appended = self.cache.ensure_capacity(r.id, eff)
            r.drawn_blocks += appended
            cow = self.cache.prepare_write(r.id, r.total_len - 1, eff)
            r.drawn_blocks += len(cow)
            pairs.extend(cow)
        self._apply_copies_locked(pairs)
        B = pad_to_bucket(len(batch), self._batch_buckets)
        # span what each row HOLDS, not just this window: an earlier
        # rejected window may have appended blocks past today's eff
        ctx = pad_to_bucket(
            max(
                max(r.total_len + dl,
                    self.cache.num_allocated(r.id) * bs)
                for r, dl in zip(batch, draft_lens)
            ),
            self._length_buckets,
        )
        nb = ctx // bs
        tokens = self._scratch_buf("vf_tokens", (B, W), np.int32)
        starts = self._scratch_buf("vf_starts", (B,), np.int32)
        dlen = self._scratch_buf("vf_dlen", (B,), np.int32)
        tables = self._scratch_buf("vf_tables", (B, nb), np.int32)
        # reused buffers: re-zero padding (a stale table row could point
        # at blocks now owned by a live sequence)
        tokens[len(batch):] = 0
        starts[len(batch):] = 0
        dlen[len(batch):] = 0
        tables[len(batch):] = 0
        for i, (r, props) in enumerate(zip(batch, proposals)):
            tokens[i, 0] = r.generated[-1] if r.generated else r.prompt[-1]
            tokens[i, 1:1 + len(props)] = props
            tokens[i, 1 + len(props):] = 0
            starts[i] = r.total_len - 1
            dlen[i] = len(props)
            tables[i] = self._table_for(r, nb)
        sample = self._sample_args_locked(batch, B)
        # verify windows need one allow-mask PER COLUMN (column s is
        # sampled from the FSM state after consuming props[:s]) — the
        # [B, W, words] leaf replaces the per-row decode mask, staged
        # all-ones for unconstrained rows so the verify pytree (and the
        # compile kind) is identical for mixed batches
        words = (self.model_cfg.vocab_size + 31) // 32
        vf_mask = self._scratch_buf("vf_mask", (B, W, words), np.uint32)
        vf_mask[:] = 0xFFFFFFFF
        for i, (r, props) in enumerate(zip(batch, proposals)):
            if r.fsm is not None:
                r.fsm.stage_verify_masks(vf_mask[i], props)
        sample["mask"] = vf_mask
        packed_dev = self.executor.verify_step(
            tokens, starts, dlen, tables, sample=sample,
        )
        packed = self._sync_verify_locked(packed_dev)
        # a completed sync proves every earlier dispatch executed
        self.cache.flush_quarantine()
        drafted = sum(draft_lens)
        accepted = 0
        step_tokens = 0
        for i, (r, dl) in enumerate(zip(batch, draft_lens)):
            # device contract: 1 <= committed <= draft_len + 1; clamp
            # anyway so a bad verdict can never overrun the budget
            committed = max(1, min(int(packed[i, 0]), dl + 1))
            accepted += committed - 1
            if r.trace_ctx:
                # traced rows carry the speculation outcome per window —
                # rendered as an engine.verify span at finish (host list
                # append only; untraced rows skip even that)
                self._tl(r, "verify_window", ts=t0_wall,
                         dur_ms=round((obs.clock() - t0) * 1000.0, 3),
                         drafted=dl, accepted=committed - 1, window=W)
            for j in range(committed):
                self._emit_token_locked(r, int(packed[i, 1 + j]))
                step_tokens += 1
                if r.done:
                    break
        self._running = [r for r in self._running if not r.done]
        self._spec_steps += 1
        self._spec_drafted_total += drafted
        self._spec_accepted_total += accepted
        self._spec_committed_total += step_tokens
        if drafted:
            self._m_spec_drafted.inc(drafted)
        if accepted:
            self._m_spec_accepted.inc(accepted)
        self._m_spec_committed.inc(step_tokens)
        dt = obs.clock() - t0
        self._m_util.set(self.cache.utilization)
        self._sync_cache_counters_locked()
        self._m_latency.observe(dt, tags={"kind": "verify"})
        self._goodput_record_locked("verify", dt, emitted + step_tokens)
        self._decode_step_window.append(dt)
        event_stats.record("llm.engine.step.verify", dt)
        self._flight_record_locked(
            "verify", t0_wall, dt, batch=len(batch), bucket_b=B,
            bucket_len=ctx, nb=nb, window=W, drafted=drafted,
            accepted=accepted, tokens=emitted + step_tokens,
            trace_ids=self._trace_ids_locked(batch),
        )

    def _sync_verify_locked(self, packed_dev) -> np.ndarray:
        """The verify-step host sync: one packed [B, W+1] int32 array
        through the same blessed channel (executor.sync_verify ->
        _host_tokens), timed and metered exactly like the token sync."""
        t0 = obs.clock()
        packed = self.executor.sync_verify(packed_dev)
        dt = obs.clock() - t0
        self._m_sync.observe(dt)
        self._m_sync_bytes.inc(packed.nbytes)
        self._sync_seconds_total += dt
        self._sync_bytes_total += packed.nbytes
        self._last_sync = {
            "sync_ms": round(dt * 1000.0, 3),
            "sync_bytes": int(packed.nbytes),
            "sync_lag": 0,
        }
        return packed

    def _sync_tokens_locked(self, tokens_dev, *, lag: int) -> np.ndarray:
        """THE device->host sync: O(batch) int32 token ids, timed and
        metered. ``lag`` says how many dispatches sat between this
        array's producing step and now (0 = prefill's immediate sync,
        1 = the pipelined decode path); it lands in the flight record so
        lagged token timestamps are explainable (docs/OBSERVABILITY.md).
        The transfer itself is the executor's ``sync_tokens``
        (executor._host_tokens — THE allowed host sync)."""
        t0 = obs.clock()
        toks = self.executor.sync_tokens(tokens_dev)
        dt = obs.clock() - t0
        self._m_sync.observe(dt)
        self._m_sync_bytes.inc(toks.nbytes)
        self._sync_seconds_total += dt
        self._sync_bytes_total += toks.nbytes
        self._last_sync = {
            "sync_ms": round(dt * 1000.0, 3),
            "sync_bytes": int(toks.nbytes),
            "sync_lag": lag,
        }
        return toks

    def _goodput_record_locked(self, kind: str, dt: float,
                               tokens: int) -> None:
        """Fold one step's (device-time, tokens) sample into the windowed
        ``llm_goodput_tokens_per_sec`` / ``llm_serving_mfu`` gauges for
        its kind. ``dt`` is the step's one-clock duration — on the
        pipelined steady path the lag-1 sync means it approximates ONE
        device step (dispatching N+1 overlaps executing N), which is
        exactly the attribution a utilization gauge wants; on lag-0
        paths (prefill, verify, drain) it includes the blocking sync
        (docs/OBSERVABILITY.md, "lag-1 caveat"). MFU is goodput times
        the analytic 2N forward FLOPs/token over the executor's peak
        FLOP rate. O(window) amortized: one append + horizon prune."""
        now = obs.clock()
        win = self._goodput_windows.get(kind)
        if win is None:
            win = self._goodput_windows[kind] = deque(maxlen=1024)
        win.append((now, float(dt), int(tokens)))
        horizon = now - _GOODPUT_WINDOW_S
        while win and win[0][0] < horizon:
            win.popleft()
        dev_s = sum(s[1] for s in win)
        toks = sum(s[2] for s in win)
        if dev_s <= 0.0 or toks <= 0:
            return
        tps = toks / dev_s
        mfu = (
            tps * self._flops_per_token / self._peak_flops
            if self._peak_flops > 0.0
            else 0.0
        )
        self._m_goodput.set(tps, tags={"kind": kind})
        self._m_mfu.set(mfu, tags={"kind": kind})
        self._goodput_last[kind] = {
            "tokens_per_sec": round(tps, 3),
            "mfu": round(mfu, 6),
            "window_steps": len(win),
            "window_device_s": round(dev_s, 6),
            "window_tokens": toks,
        }

    def _sample_args_locked(self, batch: list, B: int) -> dict:
        """Per-row sampling controls as [B] host staging arrays — the
        ``sample`` pytree consumed by ops/sampling.py inside the jitted
        step (the executor moves the leaves on-device). Padding rows are
        greedy (temperature 0) so the batch-wide all-greedy fast path
        stays available whenever every REAL row is greedy."""
        seeds = self._scratch_buf("sp_seeds", (B,), np.uint32)
        temp = self._scratch_buf("sp_temp", (B,), np.float32)
        top_k = self._scratch_buf("sp_top_k", (B,), np.int32)
        top_p = self._scratch_buf("sp_top_p", (B,), np.float32)
        # the grammar allow-mask leaf is ALWAYS staged (all-ones = no
        # constraint): mask is data, not signature, so constrained and
        # unconstrained rows share one decode program and the compile
        # kind set never grows (ops/sampling.apply_allow_mask is a
        # bitwise identity on all-ones rows)
        words = (self.model_cfg.vocab_size + 31) // 32
        mask = self._scratch_buf("sp_mask", (B, words), np.uint32)
        mask[:] = 0xFFFFFFFF
        n = len(batch)
        seeds[n:] = 0
        temp[n:] = 0.0
        top_k[n:] = 0
        top_p[n:] = 1.0
        for i, r in enumerate(batch):
            sp = r.sampling
            seeds[i] = sp.seed & 0xFFFFFFFF
            temp[i] = sp.temperature
            top_k[i] = sp.top_k
            top_p[i] = sp.top_p
            if r.fsm is not None:
                mask[i] = r.fsm.allow_row()
                self._m_masked_frac.observe(r.fsm.masked_fraction())
        return {
            "seeds": seeds,
            "temperature": temp,
            "top_k": top_k,
            "top_p": top_p,
            "mask": mask,
        }

    def _scratch_buf(self, name: str, shape: tuple, dtype) -> np.ndarray:
        """Reusable numpy staging buffer for one (name, shape) slot. TWO
        buffers alternate per slot: jnp.asarray may alias small host
        arrays zero-copy, so a buffer must not be rewritten until the
        dispatch consuming it has provably executed — under the lag-1
        pipeline a slot comes around again only after the intervening
        sync, which is exactly that proof. Callers must overwrite every
        element they use and re-zero padding tails (buffers are dirty)."""
        key = (name, shape)
        slot = self._scratch.get(key)
        if slot is None:
            slot = [np.zeros(shape, dtype), np.zeros(shape, dtype), 0]
            self._scratch[key] = slot
        slot[2] ^= 1
        return slot[slot[2]]

    def _emit_token_locked(self, r: _Request, tok: int) -> None:
        is_eos = self.cfg.eos_id is not None and tok == self.cfg.eos_id
        if r.fsm is not None and not is_eos:
            # advance the grammar cursor on the already-synced id BEFORE
            # emitting: a rejection (only reachable if on-device masking
            # degraded) terminates the stream WITHOUT the bad token, so
            # every prefix a client ever sees is grammar-valid
            if not self._advance_fsm_locked(r, tok):
                self._complete_locked(r)
                return
        r.generated.append(tok)
        now = obs.clock()
        if r.first_token_clock is None:
            r.first_token_clock = now
            self._m_ttft.observe(now - r.submitted_clock)
            self._tl(r, "first_token",
                     index=r.sampling.start_index + len(r.generated) - 1)
        else:
            self._m_tpot.observe(now - r.last_token_clock)
            self._tl(r, "token",
                     index=r.sampling.start_index + len(r.generated) - 1)
        r.last_token_clock = now
        r.out.put(tok)
        self._m_tokens.inc()
        if (
            len(r.generated) >= r.sampling.max_new_tokens
            or is_eos
            or (r.fsm is not None and r.fsm.must_stop)
            or self._hits_stop_locked(r)
        ):
            self._complete_locked(r)

    def _advance_fsm_locked(self, r: _Request, tok: int) -> bool:
        """Advance one request's grammar cursor on an emitted token id
        (host ints from the blessed sync — never a device value). With
        on-device masking a rejection here is a degradation path, so it
        is LOUD by contract: log and terminate, never emit silently."""
        try:
            ok = r.fsm.advance(tok)
        except (IndexError, TypeError, ValueError) as e:
            logger.error(
                "grammar FSM advance failed for %r on token %d: %r",
                r.id, tok, e,
            )
            return False
        if not ok:
            logger.warning(
                "grammar rejected sampled token %d for %r "
                "(state=%d, dead=%s) — terminating the stream early",
                tok, r.id, r.fsm.state, r.fsm.dead,
            )
        return ok

    def _hits_stop_locked(self, r: _Request) -> bool:
        """True when the just-emitted token completes one of the
        request's stop sequences. The match window spans the failover
        resume boundary: a resumed request's already-delivered tokens
        are its prompt tail (start_index of them), so a stop sequence
        straddling the kill point still fires on the survivor."""
        stops = r.sampling.stop
        if not stops:
            return False
        gen = r.generated
        si = r.sampling.start_index
        for seq in stops:
            L = len(seq)
            if L <= len(gen):
                if tuple(gen[-L:]) == seq:
                    return True
            else:
                need = L - len(gen)
                if si >= need and (
                    tuple(r.prompt[-need:]) + tuple(gen) == seq
                ):
                    return True
        return False

    def _complete_locked(self, r: _Request) -> None:
        r.done = True
        self._finish_obs_locked(r, "finished")
        r.out.put(_DONE)
        # last: a row completing while its next token is still in flight
        # defers the free to that step's reconcile (exactly-once release)
        self._release_blocks_locked(r)

    def _sync_cache_counters_locked(self) -> None:
        """Export cache-stat deltas to the monotonic Prometheus counters
        (cache stats are plain ints; counters are process-shared)."""
        cs = self.cache.stats
        for key, value, counter in (
            ("hit", cs.prefix_hit_tokens, self._m_hit_tokens),
            ("evict", cs.prefix_evicted_blocks, self._m_evicted),
            ("cow", cs.cow_copies, self._m_cow),
            ("prefill", self._prefill_tokens_total, self._m_prefill_tokens),
            ("demote", cs.demoted_blocks, self._m_demoted),
            ("promote", cs.promoted_blocks, self._m_promoted),
        ):
            delta = value - self._exported[key]
            if delta > 0:
                counter.inc(delta)
                self._exported[key] = value
        self._m_host_blocks.set(
            0 if self.cache.host_tier is None else self.cache.host_tier.blocks
        )

    # ---------------- observability (ISSUE 4) ----------------

    def _tl(self, r: _Request, event: str, ts: float | None = None,
            **attrs) -> None:
        """Append one phase event to a request's timeline (host list
        append — always on; the expensive part, span emission, only
        happens for traced requests at finish)."""
        e = {"event": event, "ts": obs.wall() if ts is None else ts}
        if attrs:
            e.update(attrs)
        r.timeline.append(e)

    def _timeline_dict(self, r: _Request) -> dict:
        return {
            "request_id": r.id,
            "trace_id": r.trace_ctx["trace_id"] if r.trace_ctx else None,
            "finish_reason": r.finish_reason,
            "events": list(r.timeline),
        }

    def _finish_obs_locked(self, r: _Request, reason: str) -> None:
        """Terminal bookkeeping for one request: stamp the terminal
        timeline event, archive the timeline for request_timeline(), and
        — when the submitter carried a trace context — emit the whole
        lifecycle as engine.* spans. Idempotent (failover/cancel races)."""
        if r.finish_reason is not None:
            return
        r.finish_reason = reason
        if reason == "finished":
            self._m_finished.inc()
        self._tl(r, reason, tokens=len(r.generated))
        self._timelines[r.id] = self._timeline_dict(r)
        while len(self._timelines) > self.cfg.timeline_history:
            self._timelines.popitem(last=False)
        if r.trace_ctx:
            try:
                self._emit_spans(r)
            except Exception as e:  # noqa: BLE001 — spans are best-effort
                logger.warning("span emission failed for %r: %r", r.id, e)

    def _emit_spans(self, r: _Request) -> None:
        """Turn a finished request's timeline into spans on the tracing
        plane: one ``engine.request`` parent under the submitter's span,
        with ``engine.queued``, per-chunk ``engine.prefill[_chunk]``, a
        zero-length ``engine.first_token`` marker, and one aggregate
        ``engine.decode`` child."""
        tid = r.trace_ctx["trace_id"]
        events = r.timeline
        start = events[0]["ts"]
        end = events[-1]["ts"]
        ttft_ts = next(
            (e["ts"] for e in events if e["event"] == "first_token"), None)
        root = tracing.record_span(
            "engine.request", trace_id=tid,
            parent_span_id=r.trace_ctx.get("parent_span_id"),
            start=start, end=end, kind="engine",
            attrs={
                "request_id": str(r.id),
                "finish_reason": r.finish_reason,
                "prompt_tokens": len(r.prompt),
                "cached_tokens": r.cached_tokens,
                "tokens": len(r.generated),
                "preempt_count": r.preempt_count,
                "ttft_s": (round(ttft_ts - start, 6)
                           if ttft_ts is not None else None),
            },
        )
        first_ts = last_ts = None
        decode_tokens = 0
        preempted_at: dict | None = None
        verify_windows = drafted = v_accepted = 0
        v_start = v_end = None
        for e in events:
            ev = e["event"]
            if ev == "admitted":
                tracing.record_span(
                    "engine.queued", trace_id=tid, parent_span_id=root,
                    start=start, end=e["ts"], kind="engine", attrs={},
                )
            elif ev in ("prefill", "prefill_chunk"):
                tracing.record_span(
                    f"engine.{ev}", trace_id=tid, parent_span_id=root,
                    start=e["ts"],
                    end=e["ts"] + e.get("dur_ms", 0.0) / 1000.0,
                    kind="engine",
                    attrs={"tokens": e.get("tokens"),
                           "prefill_done": e.get("prefill_done")},
                )
            elif ev == "first_token":
                first_ts = last_ts = e["ts"]
                tracing.record_span(
                    "engine.first_token", trace_id=tid,
                    parent_span_id=root, start=e["ts"], end=e["ts"],
                    kind="marker", attrs={"index": e.get("index")},
                )
            elif ev == "token":
                last_ts = e["ts"]
                decode_tokens += 1
            elif ev == "preempted":
                preempted_at = e
            elif ev == "resumed" and preempted_at is not None:
                tracing.record_span(
                    "engine.preempted", trace_id=tid, parent_span_id=root,
                    start=preempted_at["ts"], end=e["ts"], kind="engine",
                    attrs={"parked_ms": e.get("parked_ms"),
                           "priority": preempted_at.get("priority"),
                           "demoted_blocks":
                               preempted_at.get("demoted_blocks"),
                           "cached_tokens": e.get("cached_tokens")},
                )
                preempted_at = None
            elif ev == "verify_window":
                # speculation windows aggregate into ONE engine.verify
                # span (per-window spans would dwarf the decode span)
                verify_windows += 1
                drafted += e.get("drafted", 0)
                v_accepted += e.get("accepted", 0)
                if v_start is None:
                    v_start = e["ts"]
                v_end = e["ts"] + e.get("dur_ms", 0.0) / 1000.0
            elif ev == "kv_promote":
                tracing.record_span(
                    "kv.promote", trace_id=tid, parent_span_id=root,
                    start=e["ts"], end=e["ts"], kind="kv",
                    attrs={"blocks": e.get("blocks"),
                           "hit_tokens": e.get("hit_tokens")},
                )
        if preempted_at is not None:
            # still parked at finish (cancel/shutdown while preempted)
            tracing.record_span(
                "engine.preempted", trace_id=tid, parent_span_id=root,
                start=preempted_at["ts"], end=end, kind="engine",
                attrs={"priority": preempted_at.get("priority"),
                       "resumed": False},
            )
        if verify_windows:
            tracing.record_span(
                "engine.verify", trace_id=tid, parent_span_id=root,
                start=v_start, end=v_end, kind="engine",
                attrs={"windows": verify_windows, "drafted": drafted,
                       "accepted": v_accepted},
            )
        if first_ts is not None and last_ts > first_ts:
            tracing.record_span(
                "engine.decode", trace_id=tid, parent_span_id=root,
                start=first_ts, end=last_ts, kind="engine",
                attrs={"tokens": decode_tokens},
            )

    def _trace_ids_locked(self, batch) -> list[str]:
        """Trace ids of the traced requests in a step's batch (bounded),
        so a flight-recorder post-mortem links a slow step straight to
        the fleet traces that rode it. Empty for untraced traffic."""
        out = []
        for r in batch:
            if r.trace_ctx:
                out.append(r.trace_ctx["trace_id"])
                if len(out) >= 8:
                    break
        return out

    def _flight_record_locked(self, kind: str, t_wall: float, dt: float,
                              **fields) -> None:
        """One ring-buffer record per work step. O(1): a handful of int
        reads and one bounded deque append — no device access."""
        cs = self.cache.stats
        rec = {
            "kind": kind,
            "ts": round(t_wall, 6),
            "dur_ms": round(dt * 1000.0, 3),
            "admitted": self._step_admitted,
            "expired": self._step_expired,
            "cow": cs.cow_copies - self._flight_prev["cow"],
            "evicted_blocks": (
                cs.prefix_evicted_blocks - self._flight_prev["evict"]
            ),
            "kv_util": round(self.cache.utilization, 4),
            "waiting": len(self._waiting),
            "prefilling": len(self._prefilling),
            "running": len(self._running),
            # host-tier view: absolute occupancy + per-step spill churn,
            # so a post-mortem dump shows BOTH cache tiers per step
            "host_blocks": (
                0 if self.cache.host_tier is None
                else self.cache.host_tier.blocks
            ),
            "host_bytes": (
                0 if self.cache.host_tier is None
                else self.cache.host_tier.nbytes
            ),
            "demotions": cs.demoted_blocks - self._flight_prev["demote"],
            "promotions": cs.promoted_blocks - self._flight_prev["promote"],
        }
        rec.update(fields)
        if not rec.get("trace_ids"):
            rec.pop("trace_ids", None)  # untraced steps stay compact
        if self._last_sync is not None:
            # the step that PAID for a host sync carries its cost + lag
            rec.update(self._last_sync)
            self._last_sync = None
        self._flight_prev["cow"] = cs.cow_copies
        self._flight_prev["evict"] = cs.prefix_evicted_blocks
        self._flight_prev["demote"] = cs.demoted_blocks
        self._flight_prev["promote"] = cs.promoted_blocks
        self._flight.record(rec)

    def _on_new_signature(self, sig: tuple) -> None:
        """DecodeFns hook: a shape this engine has not run before — i.e.
        a compile event (programs are process-shared; this counts first
        use per engine). Tagged by shape key; also marked in the flight
        ring so a latency spike next to a compile explains itself."""
        key = obs.shape_key(sig)
        self._m_compile.inc(tags={"shape": key})
        self._flight.record(
            {"kind": "compile", "ts": obs.wall(), "shape": key}
        )

    def _dump(self, reason: str, *, path: str | None = None,
              lock_free: bool = False) -> str | None:
        """Write the flight recorder to disk. ``lock_free=True`` is the
        watchdog path: the wedged stepper may hold the lock, so only
        lock-free state goes in (the ring snapshot is GIL-atomic)."""
        extra: dict = {}
        if not lock_free:
            extra["stats"] = self.stats()
            extra["cache"] = self.cache.debug_snapshot()
        if self._last_snapshot is not None:
            # plain-attribute read: safe on the lock-free watchdog path
            extra["autoscaling_snapshot"] = self._last_snapshot
        out = obs.write_dump(
            self._flight.dump(reason, extra=extra),
            dir=self.cfg.flight_recorder_dir, path=path,
        )
        if out is not None:
            logger.warning(
                "llm engine flight recorder (%s) dumped to %s", reason, out
            )
        return out

    # ---------------- failure handling ----------------

    def _fail_engine(self, e: BaseException) -> None:
        """A step raised: fail closed. Every in-flight stream gets an
        EngineDiedError (= ActorError, so handles fail over exactly as on
        replica death) and the cache is reset best-effort."""
        if isinstance(e, EngineDiedError):
            err = e
        else:
            err = EngineDiedError(f"engine step failed: {e!r}")
            err.__cause__ = e
        with self._lock:
            self._failed = err
            if not self._dumped:
                self._dumped = True
                self._dump("engine_died")
            self._fan_out_failure(err)
        # the controller will replace this replica as soon as
        # check_health() runs — push the post-mortem spans out NOW or
        # they die in the task-event buffer with the worker
        self._flush_task_events()

    @staticmethod
    def _flush_task_events() -> None:
        from ray_tpu._private.worker import global_worker_or_none

        try:
            w = global_worker_or_none()
            if w is not None and getattr(w, "task_events", None) is not None:
                w.task_events.flush()
        except Exception as e:  # noqa: BLE001 — never fail the failure path
            logger.warning("task-event flush on engine failure: %r", e)

    def _fan_out_failure(self, err: EngineDiedError) -> None:
        for r in (list(self._waiting) + self._prefilling + self._running
                  + self._preempted):
            if not r.done:
                r.done = True
                self._finish_obs_locked(r, "failed")
                r.out.put(err)
                r.out.put(_DONE)
        self._waiting.clear()
        self._waiting_blocks = 0
        self._prefilling = []
        self._running = []
        self._preempted = []
        self._m_preempted_streams.set(0)
        self._pending = None  # in-flight step dies with the engine
        self.cache.release_all()

    # ---------------- background stepping ----------------

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._stopped or self._failed is not None:
                return
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="llm-engine-step", daemon=True
                )
                self._thread.start()
            if self._watchdog is None and self.cfg.step_timeout_s:
                self._watchdog = threading.Thread(
                    target=self._watchdog_loop,
                    name="llm-engine-watchdog",
                    daemon=True,
                )
                self._watchdog.start()

    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._stopped:
                    return
            if self._failed is not None:
                return
            try:
                progressed = self.step()
            except Exception as e:  # noqa: BLE001 — fail closed, fan out
                self._fail_engine(e)
                return
            if not progressed:
                with self._work:
                    if (
                        not self._stopped
                        and not self._waiting
                        and not self._prefilling
                        and not self._running
                        and not self._preempted
                    ):
                        self._work.wait(timeout=0.05)

    def _watchdog_loop(self) -> None:
        """Detect a wedged step. Deliberately LOCK-FREE: the failure mode
        is a jitted call stuck while holding the scheduler lock, so the
        watchdog reads ``_step_begin`` as a plain attribute and fans the
        failure out through the (thread-safe) per-request queues. The
        wedged thread still holds the lock; clients stop waiting anyway
        and the controller replaces the replica via check_health()."""
        timeout = self.cfg.step_timeout_s
        poll = max(0.005, min(0.05, timeout / 10.0))
        while not self._stopped and self._failed is None:
            begin = self._step_begin
            if begin is not None and obs.clock() - begin > timeout:
                err = EngineDiedError(
                    f"engine step wedged for > {timeout}s; "
                    "failing all in-flight streams"
                )
                self._failed = err
                if not self._dumped:
                    # lock-free by design (the wedged stepper may hold the
                    # lock): ring snapshot only, no stats()
                    self._dumped = True
                    self._dump("watchdog_timeout", lock_free=True)
                for r in (
                    list(self._waiting) + self._prefilling + self._running
                    + self._preempted
                ):
                    if not r.done:
                        r.done = True
                        r.out.put(err)
                        r.out.put(_DONE)
                self._flush_task_events()
                return
            time.sleep(poll)
