"""Continuous-batching scheduler: admission, prefill/decode interleave,
per-step join/evict, bucketed shapes.

The loop is the Orca/vLLM iteration-level scheduler: every step is EITHER
one batched prefill (admitting waiting requests) or one batched decode
step over all running sequences — new requests join the decode batch at
the next step after their prefill, finished sequences leave it the step
they complete, and their KV blocks return to the pool immediately.

TPU-first constraint: every jitted call's shape is drawn from a closed
set. Batch sizes pad to ``batch_buckets`` and token/context lengths to
``length_buckets`` (serve/_shapes.py pad_to_bucket — the same rule the
@serve.batch router uses), so total compiled programs are bounded by
2 * |batch_buckets| * |length_buckets| no matter the traffic mix
(arxiv 2011.03641: static-shape batching to stay inside the compile
cache). `DecodeFns.num_compiled_shapes` reports the realized count.

Sampling runs on host (numpy) per request — greedy, temperature, top-k —
with a per-request RNG so a sequence's output is identical whether it ran
solo or continuously batched with arbitrary neighbors.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Sequence

import numpy as np

from ray_tpu.serve._shapes import pad_to_bucket, pow2_buckets
from ray_tpu.serve.llm.decode import DecodeFns
from ray_tpu.serve.llm.kv_cache import KVCacheConfig, PagedKVCache
from ray_tpu.util import metrics

_DONE = object()  # stream sentinel


@dataclass(frozen=True)
class SamplingParams:
    max_new_tokens: int = 16
    temperature: float = 0.0  # <= 0 -> greedy
    top_k: int = 0            # 0 -> full distribution
    seed: int = 0

    def __post_init__(self):
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")


@dataclass(frozen=True)
class EngineConfig:
    model: str = "llama"          # gpt | llama (decode.py FAMILIES)
    model_config: Any = None      # GPTConfig/LlamaConfig; None -> .tiny()
    block_size: int = 16
    num_blocks: int = 64
    max_batch_size: int = 8       # max concurrently-running sequences
    max_prefill_batch: int = 4    # max admissions coalesced into one prefill
    batch_buckets: tuple[int, ...] | None = None   # None -> pow2 ladder
    length_buckets: tuple[int, ...] | None = None  # None -> pow2 ladder
    eos_id: int | None = None
    seed: int = 0                 # param init seed (when params not given)


class TokenStream:
    """Iterator over one request's generated token ids, delivered as the
    engine produces them (blocks between tokens; ends at completion)."""

    def __init__(self, request: "_Request"):
        self._request = request

    @property
    def request_id(self):
        return self._request.id

    @property
    def done(self) -> bool:
        return self._request.done

    def __iter__(self):
        while True:
            item = self._request.out.get()
            if item is _DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item


class _Request:
    __slots__ = (
        "id", "prompt", "sampling", "out", "generated", "rng",
        "reserved_blocks", "done",
    )

    def __init__(self, req_id, prompt, sampling: SamplingParams):
        self.id = req_id
        self.prompt = list(prompt)
        self.sampling = sampling
        self.out: queue.Queue = queue.Queue()
        self.generated: list[int] = []
        self.rng = np.random.default_rng(sampling.seed)
        self.reserved_blocks = 0
        self.done = False

    @property
    def total_len(self) -> int:
        return len(self.prompt) + len(self.generated)


def _sample(logits: np.ndarray, sp: SamplingParams, rng) -> int:
    """Host-side sampling from one row of f32 logits."""
    if sp.temperature <= 0.0:
        return int(np.argmax(logits))
    l = logits.astype(np.float64) / sp.temperature
    if sp.top_k > 0 and sp.top_k < l.shape[-1]:
        kth = np.partition(l, -sp.top_k)[-sp.top_k]
        l = np.where(l < kth, -np.inf, l)
    l = l - l.max()
    p = np.exp(l)
    p /= p.sum()
    return int(rng.choice(l.shape[-1], p=p))


class LLMEngine:
    """Continuous-batching inference engine over a paged KV cache.

    ``auto_step=True`` (the serving mode) runs the scheduler on a
    background thread; ``auto_step=False`` lets tests drive ``step()``
    deterministically. Only one thread may step at a time — all scheduler
    and cache state is guarded by one lock.
    """

    def __init__(
        self,
        cfg: EngineConfig | None = None,
        *,
        params: dict | None = None,
        auto_step: bool = True,
        **overrides,
    ):
        import jax

        if cfg is None:
            cfg = EngineConfig(**overrides)
        elif overrides:
            import dataclasses

            cfg = dataclasses.replace(cfg, **overrides)
        model_cfg = cfg.model_config
        if model_cfg is None:
            if cfg.model == "gpt":
                from ray_tpu.models.gpt import GPTConfig

                model_cfg = GPTConfig.tiny()
            else:
                from ray_tpu.models.llama import LlamaConfig

                model_cfg = LlamaConfig.tiny()
        self.cfg = cfg
        self.model_cfg = model_cfg
        self.fns = DecodeFns(cfg.model, model_cfg)
        self.params = (
            params
            if params is not None
            else self.fns.init(jax.random.PRNGKey(cfg.seed), model_cfg)
        )
        n_kv = getattr(model_cfg, "n_kv_head", model_cfg.n_head)
        self.cache = PagedKVCache(
            KVCacheConfig(
                n_layer=model_cfg.n_layer,
                n_kv_head=n_kv,
                head_dim=model_cfg.head_dim,
                num_blocks=cfg.num_blocks,
                block_size=cfg.block_size,
                dtype=model_cfg.dtype,
            )
        )
        self._batch_buckets = cfg.batch_buckets or pow2_buckets(
            1, cfg.max_batch_size
        )
        self._length_buckets = cfg.length_buckets or pow2_buckets(
            cfg.block_size, model_cfg.max_seq_len
        )
        for b in self._length_buckets:
            if b % cfg.block_size:
                raise ValueError(
                    f"length bucket {b} is not a multiple of "
                    f"block_size={cfg.block_size}"
                )
        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self._waiting: deque[_Request] = deque()
        self._running: list[_Request] = []
        self._next_id = 0
        self._auto_step = auto_step
        self._thread: threading.Thread | None = None
        self._stopped = False

        self._m_tokens = metrics.counter(
            "llm_engine_tokens_generated",
            "Tokens generated by the serve/llm engine",
        )
        self._m_queue = metrics.gauge(
            "llm_engine_queue_depth", "Requests waiting for admission"
        )
        self._m_util = metrics.gauge(
            "llm_engine_kv_block_utilization",
            "Fraction of usable KV blocks allocated",
        )
        self._m_latency = metrics.histogram(
            "llm_engine_step_latency_seconds",
            "Engine step latency by kind (prefill/decode)",
            boundaries=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0),
            tag_keys=("kind",),
        )

    # ---------------- public API ----------------

    def submit(
        self,
        prompt: Sequence[int],
        sampling: SamplingParams | None = None,
        **sampling_overrides,
    ) -> TokenStream:
        """Enqueue one request; returns a stream of generated token ids."""
        if sampling is None:
            sampling = SamplingParams(**sampling_overrides)
        elif sampling_overrides:
            import dataclasses

            sampling = dataclasses.replace(sampling, **sampling_overrides)
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must contain at least one token")
        total = len(prompt) + sampling.max_new_tokens
        if total > self.model_cfg.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({sampling.max_new_tokens}) exceeds model max_seq_len "
                f"{self.model_cfg.max_seq_len}"
            )
        if self.cache.cfg.blocks_for(total) > self.cache.cfg.usable_blocks:
            raise ValueError(
                f"request needs {self.cache.cfg.blocks_for(total)} KV blocks "
                f"but the pool only has {self.cache.cfg.usable_blocks}"
            )
        with self._lock:
            if self._stopped:
                raise RuntimeError("engine is shut down")
            req = _Request(self._next_id, prompt, sampling)
            self._next_id += 1
            self._waiting.append(req)
            self._m_queue.set(len(self._waiting))
            self._work.notify_all()
        if self._auto_step:
            self._ensure_thread()
        return TokenStream(req)

    def generate(
        self,
        prompt: Sequence[int],
        sampling: SamplingParams | None = None,
        **sampling_overrides,
    ) -> list[int]:
        """Synchronous convenience: submit and collect all tokens."""
        stream = self.submit(prompt, sampling, **sampling_overrides)
        if not self._auto_step:
            while not stream.done:
                if not self.step():
                    break  # pragma: no cover — queue drained early
        return list(stream)

    def step(self) -> bool:
        """One scheduler iteration: a batched prefill if any request can be
        admitted, else a batched decode step. Returns False when idle."""
        with self._lock:
            admitted = self._admit_locked()
            if admitted:
                self._prefill_locked(admitted)
                return True
            if self._running:
                self._decode_locked()
                return True
            return False

    def stats(self) -> dict:
        with self._lock:
            return {
                "waiting": len(self._waiting),
                "running": len(self._running),
                "kv_used_blocks": self.cache.used_blocks,
                "kv_utilization": self.cache.utilization,
                "kv_high_water_blocks": self.cache.stats.high_water_blocks,
                "num_compiled_shapes": self.fns.num_compiled_shapes,
            }

    @property
    def num_compiled_shapes(self) -> int:
        return self.fns.num_compiled_shapes

    def shutdown(self) -> None:
        with self._lock:
            self._stopped = True
            for r in list(self._waiting) + self._running:
                if not r.done:
                    r.done = True
                    r.out.put(_DONE)
            self._waiting.clear()
            self._running.clear()
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # ---------------- scheduler internals (lock held) ----------------

    def _admit_locked(self) -> list[_Request]:
        admitted: list[_Request] = []
        while (
            self._waiting
            and len(self._running) + len(admitted) < self.cfg.max_batch_size
            and len(admitted) < self.cfg.max_prefill_batch
        ):
            req = self._waiting[0]
            need = self.cache.cfg.blocks_for(
                len(req.prompt) + req.sampling.max_new_tokens
            )
            if not self.cache.can_reserve(need):
                break  # blocks free up when a running sequence completes
            self.cache.reserve(need)
            req.reserved_blocks = need
            admitted.append(self._waiting.popleft())
        if admitted:
            self._m_queue.set(len(self._waiting))
        return admitted

    def _prefill_locked(self, admitted: list[_Request]) -> None:
        import jax.numpy as jnp

        t0 = time.perf_counter()
        bs = self.cfg.block_size
        for r in admitted:
            self.cache.allocate(r.id)
            self.cache.ensure_capacity(r.id, len(r.prompt))
        S = pad_to_bucket(
            max(len(r.prompt) for r in admitted), self._length_buckets
        )
        B = pad_to_bucket(len(admitted), self._batch_buckets)
        nb = S // bs
        tokens = np.zeros((B, S), np.int32)
        lengths = np.ones((B,), np.int32)  # padding rows: length 1
        tables = np.zeros((B, nb), np.int32)
        for i, r in enumerate(admitted):
            tokens[i, : len(r.prompt)] = r.prompt
            lengths[i] = len(r.prompt)
            tables[i] = self.cache.block_table(r.id, nb)
        logits, self.cache.k, self.cache.v = self.fns.prefill(
            self.params, self.cache.k, self.cache.v,
            jnp.asarray(tokens), jnp.asarray(lengths), jnp.asarray(tables),
        )
        logits = np.asarray(logits, np.float32)
        for i, r in enumerate(admitted):
            self._emit_locked(r, logits[i])
            if not r.done:
                self._running.append(r)
        self._m_util.set(self.cache.utilization)
        self._m_latency.observe(
            time.perf_counter() - t0, tags={"kind": "prefill"}
        )

    def _decode_locked(self) -> None:
        import jax.numpy as jnp

        t0 = time.perf_counter()
        bs = self.cfg.block_size
        batch = list(self._running)
        for r in batch:
            self.cache.ensure_capacity(r.id, r.total_len)
        B = pad_to_bucket(len(batch), self._batch_buckets)
        ctx = pad_to_bucket(
            max(r.total_len for r in batch), self._length_buckets
        )
        nb = ctx // bs
        tokens = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        tables = np.zeros((B, nb), np.int32)
        for i, r in enumerate(batch):
            tokens[i] = r.generated[-1] if r.generated else r.prompt[-1]
            positions[i] = r.total_len - 1
            tables[i] = self.cache.block_table(r.id, nb)
        logits, self.cache.k, self.cache.v = self.fns.decode(
            self.params, self.cache.k, self.cache.v,
            jnp.asarray(tokens), jnp.asarray(positions), jnp.asarray(tables),
        )
        logits = np.asarray(logits, np.float32)
        for i, r in enumerate(batch):
            self._emit_locked(r, logits[i])
        self._running = [r for r in self._running if not r.done]
        self._m_util.set(self.cache.utilization)
        self._m_latency.observe(
            time.perf_counter() - t0, tags={"kind": "decode"}
        )

    def _emit_locked(self, r: _Request, logits_row: np.ndarray) -> None:
        tok = _sample(logits_row, r.sampling, r.rng)
        r.generated.append(tok)
        r.out.put(tok)
        self._m_tokens.inc()
        if (
            len(r.generated) >= r.sampling.max_new_tokens
            or (self.cfg.eos_id is not None and tok == self.cfg.eos_id)
        ):
            self._complete_locked(r)

    def _complete_locked(self, r: _Request) -> None:
        leftover = r.reserved_blocks - self.cache.num_allocated(r.id)
        self.cache.free(r.id)
        if leftover > 0:
            self.cache.release_reservation(leftover)
        r.done = True
        r.out.put(_DONE)
        self._work.notify_all()  # freed blocks may unblock admissions

    # ---------------- background stepping ----------------

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is not None or self._stopped:
                return
            self._thread = threading.Thread(
                target=self._loop, name="llm-engine-step", daemon=True
            )
            self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._stopped:
                    return
            try:
                progressed = self.step()
            except Exception as e:  # noqa: BLE001 — fan out to all streams
                with self._lock:
                    for r in list(self._waiting) + self._running:
                        if not r.done:
                            r.done = True
                            r.out.put(e)
                            r.out.put(_DONE)
                    self._waiting.clear()
                    self._running.clear()
                continue
            if not progressed:
                with self._work:
                    if (
                        not self._stopped
                        and not self._waiting
                        and not self._running
                    ):
                        self._work.wait(timeout=0.05)
