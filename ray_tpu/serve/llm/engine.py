"""Continuous-batching scheduler: admission, prefill/decode interleave,
per-step join/evict, bucketed shapes.

The loop is the Orca/vLLM iteration-level scheduler: every step is EITHER
one batched prefill (admitting waiting requests) or one batched decode
step over all running sequences — new requests join the decode batch at
the next step after their prefill, finished sequences leave it the step
they complete, and their KV blocks return to the pool immediately.

TPU-first constraint: every jitted call's shape is drawn from a closed
set. Batch sizes pad to ``batch_buckets`` and token/context lengths to
``length_buckets`` (serve/_shapes.py pad_to_bucket — the same rule the
@serve.batch router uses), so total compiled programs are bounded by
2 * |batch_buckets| * |length_buckets| no matter the traffic mix
(arxiv 2011.03641: static-shape batching to stay inside the compile
cache). `DecodeFns.num_compiled_shapes` reports the realized count.

Sampling runs on host (numpy) per request — greedy, temperature, top-k —
with a per-request RNG so a sequence's output is identical whether it ran
solo or continuously batched with arbitrary neighbors. The RNG consumes
exactly one uniform per token, which is what makes mid-stream failover
byte-identical: a resumed request sets ``start_index`` and the fresh
engine fast-forwards the RNG past the tokens already delivered.

Failure semantics (docs/SERVING_LLM.md "Failure semantics"):

- ``submit`` applies admission control: a bounded waiting queue
  (``max_waiting``) and an optional worst-case block budget for queued
  work (``max_waiting_blocks``), rejecting with ``EngineOverloadedError``
  rather than queueing unboundedly.
- per-request deadlines (``SamplingParams.deadline_s``) are enforced at
  the top of every step; expired sequences are evicted and their streams
  fail with ``DeadlineExceededError``.
- ``cancel(request_id)`` evicts a waiting or running sequence and returns
  its KV blocks (allocation AND leftover reservation) immediately.
- if a step raises, or wedges past ``step_timeout_s`` (watchdog thread),
  the engine fails closed: every in-flight stream gets an
  ``EngineDiedError`` (an ``ActorError`` — clients treat it exactly like
  replica death and fail over) instead of blocking forever.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

from ray_tpu._private import chaos
from ray_tpu.exceptions import (
    DeadlineExceededError,
    EngineDiedError,
    EngineOverloadedError,
    RequestCancelledError,
)
from ray_tpu.serve._shapes import pad_to_bucket, pow2_buckets
from ray_tpu.serve.llm.decode import DecodeFns
from ray_tpu.serve.llm.kv_cache import KVCacheConfig, PagedKVCache
from ray_tpu.util import metrics

_DONE = object()  # stream sentinel


@dataclass(frozen=True)
class SamplingParams:
    max_new_tokens: int = 16
    temperature: float = 0.0  # <= 0 -> greedy
    top_k: int = 0            # 0 -> full distribution
    seed: int = 0
    deadline_s: float | None = None  # wall-clock budget from submit()
    start_index: int = 0      # tokens already delivered (failover resume)

    def __post_init__(self):
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.start_index < 0:
            raise ValueError("start_index must be >= 0")


@dataclass(frozen=True)
class EngineConfig:
    model: str = "llama"          # gpt | llama (decode.py FAMILIES)
    model_config: Any = None      # GPTConfig/LlamaConfig; None -> .tiny()
    block_size: int = 16
    num_blocks: int = 64
    max_batch_size: int = 8       # max concurrently-running sequences
    max_prefill_batch: int = 4    # max admissions coalesced into one prefill
    batch_buckets: tuple[int, ...] | None = None   # None -> pow2 ladder
    length_buckets: tuple[int, ...] | None = None  # None -> pow2 ladder
    eos_id: int | None = None
    seed: int = 0                 # param init seed (when params not given)
    max_waiting: int = 128        # admission queue bound (overload beyond)
    max_waiting_blocks: int | None = None  # worst-case block budget queued
    step_timeout_s: float | None = None    # watchdog: wedged-step ceiling


class TokenStream:
    """Iterator over one request's generated token ids, delivered as the
    engine produces them (blocks between tokens; ends at completion)."""

    def __init__(self, request: "_Request"):
        self._request = request

    @property
    def request_id(self):
        return self._request.id

    @property
    def done(self) -> bool:
        return self._request.done

    def __iter__(self):
        while True:
            item = self._request.out.get()
            if item is _DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item


class _Request:
    __slots__ = (
        "id", "prompt", "sampling", "out", "generated", "rng",
        "reserved_blocks", "done", "deadline",
    )

    def __init__(self, req_id, prompt, sampling: SamplingParams):
        self.id = req_id
        self.prompt = list(prompt)
        self.sampling = sampling
        self.out: queue.Queue = queue.Queue()
        self.generated: list[int] = []
        self.rng = np.random.default_rng(sampling.seed)
        if sampling.start_index:
            # one uniform per token (see _sample): skipping start_index
            # draws resumes the stream exactly where the dead replica left it
            self.rng.random(sampling.start_index)
        self.reserved_blocks = 0
        self.done = False
        self.deadline = (
            time.monotonic() + sampling.deadline_s
            if sampling.deadline_s is not None
            else None
        )

    @property
    def total_len(self) -> int:
        return len(self.prompt) + len(self.generated)


def _sample(logits: np.ndarray, sp: SamplingParams, rng) -> int:
    """Host-side sampling from one row of f32 logits.

    Consumes exactly ONE uniform per token (inverse-CDF draw) — greedy
    consumes none — so a request's RNG position is a pure function of how
    many tokens it has produced. Mid-stream failover relies on this:
    re-prefilling ``prompt + generated`` on a fresh engine with
    ``start_index=len(generated)`` reproduces the remaining tokens
    byte-identically.
    """
    if sp.temperature <= 0.0:
        return int(np.argmax(logits))
    l = logits.astype(np.float64) / sp.temperature
    if sp.top_k > 0 and sp.top_k < l.shape[-1]:
        kth = np.partition(l, -sp.top_k)[-sp.top_k]
        l = np.where(l < kth, -np.inf, l)
    l = l - l.max()
    p = np.exp(l)
    p /= p.sum()
    u = rng.random()
    return int(
        min(np.searchsorted(np.cumsum(p), u, side="right"), l.shape[-1] - 1)
    )


class LLMEngine:
    """Continuous-batching inference engine over a paged KV cache.

    ``auto_step=True`` (the serving mode) runs the scheduler on a
    background thread; ``auto_step=False`` lets tests drive ``step()``
    deterministically. Only one thread may step at a time — all scheduler
    and cache state is guarded by one lock.
    """

    def __init__(
        self,
        cfg: EngineConfig | None = None,
        *,
        params: dict | None = None,
        auto_step: bool = True,
        **overrides,
    ):
        import jax

        if cfg is None:
            cfg = EngineConfig(**overrides)
        elif overrides:
            import dataclasses

            cfg = dataclasses.replace(cfg, **overrides)
        model_cfg = cfg.model_config
        if model_cfg is None:
            if cfg.model == "gpt":
                from ray_tpu.models.gpt import GPTConfig

                model_cfg = GPTConfig.tiny()
            else:
                from ray_tpu.models.llama import LlamaConfig

                model_cfg = LlamaConfig.tiny()
        self.cfg = cfg
        self.model_cfg = model_cfg
        self.fns = DecodeFns(cfg.model, model_cfg)
        self.params = (
            params
            if params is not None
            else self.fns.init(jax.random.PRNGKey(cfg.seed), model_cfg)
        )
        n_kv = getattr(model_cfg, "n_kv_head", model_cfg.n_head)
        self.cache = PagedKVCache(
            KVCacheConfig(
                n_layer=model_cfg.n_layer,
                n_kv_head=n_kv,
                head_dim=model_cfg.head_dim,
                num_blocks=cfg.num_blocks,
                block_size=cfg.block_size,
                dtype=model_cfg.dtype,
            )
        )
        self._batch_buckets = cfg.batch_buckets or pow2_buckets(
            1, cfg.max_batch_size
        )
        self._length_buckets = cfg.length_buckets or pow2_buckets(
            cfg.block_size, model_cfg.max_seq_len
        )
        for b in self._length_buckets:
            if b % cfg.block_size:
                raise ValueError(
                    f"length bucket {b} is not a multiple of "
                    f"block_size={cfg.block_size}"
                )
        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        self._waiting: deque[_Request] = deque()
        self._waiting_blocks = 0  # worst-case blocks held by the queue
        self._running: list[_Request] = []
        self._next_id = 0
        self._auto_step = auto_step
        self._thread: threading.Thread | None = None
        self._watchdog: threading.Thread | None = None
        self._stopped = False
        # Set by _fail_engine / the watchdog; read WITHOUT the lock (the
        # whole point is surviving a step that wedged while holding it).
        self._failed: EngineDiedError | None = None
        # perf_counter() at step entry, None when no step is in flight —
        # plain attribute so the watchdog can read it lock-free.
        self._step_begin: float | None = None
        self._rejected_total = 0
        self._cancelled_total = 0
        self._deadline_total = 0

        self._m_tokens = metrics.counter(
            "llm_engine_tokens_generated",
            "Tokens generated by the serve/llm engine",
        )
        self._m_queue = metrics.gauge(
            "llm_engine_queue_depth", "Requests waiting for admission"
        )
        self._m_util = metrics.gauge(
            "llm_engine_kv_block_utilization",
            "Fraction of usable KV blocks allocated",
        )
        self._m_latency = metrics.histogram(
            "llm_engine_step_latency_seconds",
            "Engine step latency by kind (prefill/decode)",
            boundaries=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0),
            tag_keys=("kind",),
        )
        self._m_rejected = metrics.counter(
            "llm_requests_rejected",
            "Requests rejected by engine admission control (overload)",
        )
        self._m_cancelled = metrics.counter(
            "llm_requests_cancelled",
            "Requests cancelled (client disconnect / explicit cancel)",
        )
        self._m_deadline = metrics.counter(
            "llm_deadline_exceeded",
            "Requests evicted because deadline_s expired mid-generation",
        )

    # ---------------- public API ----------------

    def submit(
        self,
        prompt: Sequence[int],
        sampling: SamplingParams | None = None,
        **sampling_overrides,
    ) -> TokenStream:
        """Enqueue one request; returns a stream of generated token ids.

        Raises ``EngineOverloadedError`` when admission control rejects
        (waiting queue full, or queued worst-case blocks over budget) and
        ``EngineDiedError`` when the engine has already failed.
        """
        if sampling is None:
            sampling = SamplingParams(**sampling_overrides)
        elif sampling_overrides:
            import dataclasses

            sampling = dataclasses.replace(sampling, **sampling_overrides)
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must contain at least one token")
        total = len(prompt) + sampling.max_new_tokens
        if total > self.model_cfg.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({sampling.max_new_tokens}) exceeds model max_seq_len "
                f"{self.model_cfg.max_seq_len}"
            )
        need = self.cache.cfg.blocks_for(total)
        if need > self.cache.cfg.usable_blocks:
            raise ValueError(
                f"request needs {need} KV blocks "
                f"but the pool only has {self.cache.cfg.usable_blocks}"
            )
        if self._failed is not None:
            raise self._failed
        with self._lock:
            if self._stopped:
                raise RuntimeError("engine is shut down")
            if len(self._waiting) >= self.cfg.max_waiting or (
                self.cfg.max_waiting_blocks is not None
                and self._waiting_blocks + need > self.cfg.max_waiting_blocks
            ):
                self._rejected_total += 1
                self._m_rejected.inc()
                raise EngineOverloadedError(
                    f"admission queue full ({len(self._waiting)} waiting, "
                    f"{self._waiting_blocks} worst-case blocks queued); "
                    "retry later"
                )
            req = _Request(self._next_id, prompt, sampling)
            self._next_id += 1
            self._waiting.append(req)
            self._waiting_blocks += need
            self._m_queue.set(len(self._waiting))
            self._work.notify_all()
        if self._auto_step:
            self._ensure_thread()
        return TokenStream(req)

    def generate(
        self,
        prompt: Sequence[int],
        sampling: SamplingParams | None = None,
        **sampling_overrides,
    ) -> list[int]:
        """Synchronous convenience: submit and collect all tokens."""
        stream = self.submit(prompt, sampling, **sampling_overrides)
        if not self._auto_step:
            while not stream.done:
                if not self.step():
                    break  # pragma: no cover — queue drained early
        return list(stream)

    def step(self) -> bool:
        """One scheduler iteration: expire deadlines, then a batched
        prefill if any request can be admitted, else a batched decode
        step. Returns False when idle."""
        with self._lock:
            self._step_begin = time.perf_counter()
            try:
                chaos.fire("engine.step")
                self._expire_deadlines_locked()
                admitted = self._admit_locked()
                if admitted:
                    self._prefill_locked(admitted)
                    return True
                if self._running:
                    self._decode_locked()
                    return True
                return False
            finally:
                self._step_begin = None

    def cancel(self, request_id) -> bool:
        """Evict a waiting/running request, fail its stream with
        ``RequestCancelledError``, and return its KV blocks immediately.
        Returns False when the request is unknown or already finished
        (idempotent — safe to broadcast to every replica)."""
        with self._lock:
            req = self._find_locked(request_id)
            if req is None:
                return False
            self._evict_locked(req)
            self._cancelled_total += 1
            self._m_cancelled.inc()
            req.out.put(
                RequestCancelledError(f"request {request_id!r} cancelled")
            )
            req.out.put(_DONE)
            return True

    def stats(self) -> dict:
        with self._lock:
            return {
                "waiting": len(self._waiting),
                "running": len(self._running),
                "kv_used_blocks": self.cache.used_blocks,
                "kv_utilization": self.cache.utilization,
                "kv_high_water_blocks": self.cache.stats.high_water_blocks,
                "num_compiled_shapes": self.fns.num_compiled_shapes,
                "rejected_total": self._rejected_total,
                "cancelled_total": self._cancelled_total,
                "deadline_exceeded_total": self._deadline_total,
                "failed": self._failed is not None,
            }

    @property
    def num_compiled_shapes(self) -> int:
        return self.fns.num_compiled_shapes

    @property
    def failed(self) -> bool:
        return self._failed is not None

    def shutdown(self) -> None:
        """Stop stepping, fail every pending stream with a clear error,
        and return ALL KV blocks (allocations and reservations) to the
        pool — repeated create/shutdown in one process is leak-free."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            err = RequestCancelledError("engine shut down")
            for r in list(self._waiting) + self._running:
                if not r.done:
                    r.done = True
                    r.out.put(err)
                    r.out.put(_DONE)
            self.cache.release_all()
            self._waiting.clear()
            self._waiting_blocks = 0
            self._running.clear()
            self._m_queue.set(0)
            self._m_util.set(self.cache.utilization)
            self._work.notify_all()
        for t in (self._thread, self._watchdog):
            if t is not None:
                t.join(timeout=5)
        self._thread = None
        self._watchdog = None

    # ---------------- scheduler internals (lock held) ----------------

    def _find_locked(self, request_id) -> _Request | None:
        for r in self._running:
            if r.id == request_id:
                return r
        for r in self._waiting:
            if r.id == request_id:
                return r
        return None

    def _evict_locked(self, r: _Request) -> None:
        """Remove a live request from the scheduler and return its blocks
        (allocation + leftover reservation for running; queued worst-case
        budget for waiting). Does NOT touch the output stream."""
        if r in self._running:
            self._running.remove(r)
            leftover = r.reserved_blocks - self.cache.num_allocated(r.id)
            self.cache.free(r.id)
            if leftover > 0:
                self.cache.release_reservation(leftover)
        else:
            try:
                self._waiting.remove(r)
            except ValueError:  # pragma: no cover — already gone
                pass
            else:
                self._waiting_blocks -= self.cache.cfg.blocks_for(
                    len(r.prompt) + r.sampling.max_new_tokens
                )
        r.done = True
        self._m_queue.set(len(self._waiting))
        self._m_util.set(self.cache.utilization)
        self._work.notify_all()  # freed blocks may unblock admissions

    def _expire_deadlines_locked(self) -> None:
        now = time.monotonic()
        for r in [
            r
            for r in list(self._waiting) + self._running
            if r.deadline is not None and now >= r.deadline
        ]:
            self._evict_locked(r)
            self._deadline_total += 1
            self._m_deadline.inc()
            r.out.put(
                DeadlineExceededError(
                    f"request {r.id!r} deadline "
                    f"({r.sampling.deadline_s}s) expired after "
                    f"{len(r.generated)} tokens"
                )
            )
            r.out.put(_DONE)

    def _admit_locked(self) -> list[_Request]:
        admitted: list[_Request] = []
        while (
            self._waiting
            and len(self._running) + len(admitted) < self.cfg.max_batch_size
            and len(admitted) < self.cfg.max_prefill_batch
        ):
            req = self._waiting[0]
            need = self.cache.cfg.blocks_for(
                len(req.prompt) + req.sampling.max_new_tokens
            )
            if not self.cache.can_reserve(need):
                break  # blocks free up when a running sequence completes
            self.cache.reserve(need)
            req.reserved_blocks = need
            admitted.append(self._waiting.popleft())
            self._waiting_blocks -= need
        if admitted:
            self._m_queue.set(len(self._waiting))
        return admitted

    def _prefill_locked(self, admitted: list[_Request]) -> None:
        import jax.numpy as jnp

        chaos.fire("engine.prefill", batch=len(admitted))
        t0 = time.perf_counter()
        bs = self.cfg.block_size
        for r in admitted:
            self.cache.allocate(r.id)
            self.cache.ensure_capacity(r.id, len(r.prompt))
        S = pad_to_bucket(
            max(len(r.prompt) for r in admitted), self._length_buckets
        )
        B = pad_to_bucket(len(admitted), self._batch_buckets)
        nb = S // bs
        tokens = np.zeros((B, S), np.int32)
        lengths = np.ones((B,), np.int32)  # padding rows: length 1
        tables = np.zeros((B, nb), np.int32)
        for i, r in enumerate(admitted):
            tokens[i, : len(r.prompt)] = r.prompt
            lengths[i] = len(r.prompt)
            tables[i] = self.cache.block_table(r.id, nb)
        logits, self.cache.k, self.cache.v = self.fns.prefill(
            self.params, self.cache.k, self.cache.v,
            jnp.asarray(tokens), jnp.asarray(lengths), jnp.asarray(tables),
        )
        logits = np.asarray(logits, np.float32)
        for i, r in enumerate(admitted):
            self._emit_locked(r, logits[i])
            if not r.done:
                self._running.append(r)
        self._m_util.set(self.cache.utilization)
        self._m_latency.observe(
            time.perf_counter() - t0, tags={"kind": "prefill"}
        )

    def _decode_locked(self) -> None:
        import jax.numpy as jnp

        chaos.fire("engine.decode", batch=len(self._running))
        t0 = time.perf_counter()
        bs = self.cfg.block_size
        batch = list(self._running)
        for r in batch:
            self.cache.ensure_capacity(r.id, r.total_len)
        B = pad_to_bucket(len(batch), self._batch_buckets)
        ctx = pad_to_bucket(
            max(r.total_len for r in batch), self._length_buckets
        )
        nb = ctx // bs
        tokens = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        tables = np.zeros((B, nb), np.int32)
        for i, r in enumerate(batch):
            tokens[i] = r.generated[-1] if r.generated else r.prompt[-1]
            positions[i] = r.total_len - 1
            tables[i] = self.cache.block_table(r.id, nb)
        logits, self.cache.k, self.cache.v = self.fns.decode(
            self.params, self.cache.k, self.cache.v,
            jnp.asarray(tokens), jnp.asarray(positions), jnp.asarray(tables),
        )
        logits = np.asarray(logits, np.float32)
        for i, r in enumerate(batch):
            self._emit_locked(r, logits[i])
        self._running = [r for r in self._running if not r.done]
        self._m_util.set(self.cache.utilization)
        self._m_latency.observe(
            time.perf_counter() - t0, tags={"kind": "decode"}
        )

    def _emit_locked(self, r: _Request, logits_row: np.ndarray) -> None:
        tok = _sample(logits_row, r.sampling, r.rng)
        r.generated.append(tok)
        r.out.put(tok)
        self._m_tokens.inc()
        if (
            len(r.generated) >= r.sampling.max_new_tokens
            or (self.cfg.eos_id is not None and tok == self.cfg.eos_id)
        ):
            self._complete_locked(r)

    def _complete_locked(self, r: _Request) -> None:
        leftover = r.reserved_blocks - self.cache.num_allocated(r.id)
        self.cache.free(r.id)
        if leftover > 0:
            self.cache.release_reservation(leftover)
        r.done = True
        r.out.put(_DONE)
        self._work.notify_all()  # freed blocks may unblock admissions

    # ---------------- failure handling ----------------

    def _fail_engine(self, e: BaseException) -> None:
        """A step raised: fail closed. Every in-flight stream gets an
        EngineDiedError (= ActorError, so handles fail over exactly as on
        replica death) and the cache is reset best-effort."""
        if isinstance(e, EngineDiedError):
            err = e
        else:
            err = EngineDiedError(f"engine step failed: {e!r}")
            err.__cause__ = e
        with self._lock:
            self._failed = err
            self._fan_out_failure(err)

    def _fan_out_failure(self, err: EngineDiedError) -> None:
        for r in list(self._waiting) + list(self._running):
            if not r.done:
                r.done = True
                r.out.put(err)
                r.out.put(_DONE)
        self._waiting.clear()
        self._waiting_blocks = 0
        self._running = []
        self.cache.release_all()

    # ---------------- background stepping ----------------

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._stopped or self._failed is not None:
                return
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="llm-engine-step", daemon=True
                )
                self._thread.start()
            if self._watchdog is None and self.cfg.step_timeout_s:
                self._watchdog = threading.Thread(
                    target=self._watchdog_loop,
                    name="llm-engine-watchdog",
                    daemon=True,
                )
                self._watchdog.start()

    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._stopped:
                    return
            if self._failed is not None:
                return
            try:
                progressed = self.step()
            except Exception as e:  # noqa: BLE001 — fail closed, fan out
                self._fail_engine(e)
                return
            if not progressed:
                with self._work:
                    if (
                        not self._stopped
                        and not self._waiting
                        and not self._running
                    ):
                        self._work.wait(timeout=0.05)

    def _watchdog_loop(self) -> None:
        """Detect a wedged step. Deliberately LOCK-FREE: the failure mode
        is a jitted call stuck while holding the scheduler lock, so the
        watchdog reads ``_step_begin`` as a plain attribute and fans the
        failure out through the (thread-safe) per-request queues. The
        wedged thread still holds the lock; clients stop waiting anyway
        and the controller replaces the replica via check_health()."""
        timeout = self.cfg.step_timeout_s
        poll = max(0.005, min(0.05, timeout / 10.0))
        while not self._stopped and self._failed is None:
            begin = self._step_begin
            if begin is not None and time.perf_counter() - begin > timeout:
                err = EngineDiedError(
                    f"engine step wedged for > {timeout}s; "
                    "failing all in-flight streams"
                )
                self._failed = err
                for r in list(self._waiting) + list(self._running):
                    if not r.done:
                        r.done = True
                        r.out.put(err)
                        r.out.put(_DONE)
                return
            time.sleep(poll)
