"""Instrumentation plane for serve/llm: one clock, serving-latency
histograms, and the engine flight recorder.

Design constraints (ISSUE 4 / docs/OBSERVABILITY.md):

- **One clock.** Every duration the engine records — step latency
  histograms, flight-recorder records, event_stats — flows through
  ``clock()`` (monotonic), and every absolute timestamp (timelines,
  spans, chrome export) through ``wall()``. tests/test_sanitizers.py
  lints serve/llm for stray ``time.time()`` / ``time.perf_counter()``
  calls outside this module, so the records can never disagree about
  what was measured.
- **Zero device syncs.** Nothing here touches jax values; the engine's
  single device->host sync point (``_host_tokens``) is unchanged.
- **O(1) per step.** The flight recorder is a ``deque(maxlen=N)`` ring:
  one dict append per step, old records drop off the far end. Dumping is
  a read-only snapshot, safe from the lock-free watchdog thread (a
  ``list(deque)`` copy is atomic under the GIL) — the whole point is
  explaining a step that wedged while holding the scheduler lock.
"""
from __future__ import annotations

import json
import logging
import os
import tempfile
import time
from collections import deque

from ray_tpu._private import event_stats
from ray_tpu.util import metrics

logger = logging.getLogger("ray_tpu.serve.llm")

# THE two clocks: monotonic for durations, wall for timestamps that must
# line up across processes (timelines, spans, chrome export).
clock = time.perf_counter
wall = time.time

# Serving-appropriate buckets: TTFT spans "prefix-hit tiny model" (ms) to
# "cold 70B prefill" (tens of seconds); per-output-token tracks decode
# step cadence; queue wait tracks admission backpressure.
TTFT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)
TPOT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0)
QUEUE_WAIT_BUCKETS = (0.0005, 0.002, 0.01, 0.05, 0.2, 1.0, 5.0, 20.0)
# The single O(batch) device->host sync (engine._host_tokens): sub-ms on
# the pipelined steady state, device-step-sized when the lag collapses.
HOST_SYNC_BUCKETS = (
    0.00005, 0.0002, 0.001, 0.005, 0.02, 0.1, 0.5, 2.0,
)


def ttft_histogram() -> metrics.Histogram:
    return metrics.histogram(
        "llm_ttft_seconds",
        "Time from submit() to the first generated token",
        boundaries=TTFT_BUCKETS,
    )


def tpot_histogram() -> metrics.Histogram:
    return metrics.histogram(
        "llm_time_per_output_token_seconds",
        "Gap between consecutive generated tokens of one request",
        boundaries=TPOT_BUCKETS,
    )


def queue_wait_histogram() -> metrics.Histogram:
    return metrics.histogram(
        "llm_queue_wait_seconds",
        "Time a request waited for admission (submit -> admitted)",
        boundaries=QUEUE_WAIT_BUCKETS,
    )


def host_sync_histogram() -> metrics.Histogram:
    return metrics.histogram(
        "llm_host_sync_seconds",
        "Time blocked in the engine's single device->host token sync",
        boundaries=HOST_SYNC_BUCKETS,
    )


def sync_bytes_counter() -> metrics.Counter:
    return metrics.counter(
        "llm_sync_bytes",
        "Bytes crossed device->host at the engine's token sync point "
        "(O(batch) int32 per step under fused sampling)",
    )


def goodput_gauge() -> metrics.Gauge:
    return metrics.gauge(
        "llm_goodput_tokens_per_sec",
        "Windowed serving goodput: tokens retired per second of "
        "attributed device time, by step kind",
        tag_keys=("kind",),
    )


def mfu_gauge() -> metrics.Gauge:
    return metrics.gauge(
        "llm_serving_mfu",
        "Windowed serving model-FLOPs utilization: goodput x 2*n_params "
        "FLOPs/token over the executor's peak FLOP rate, by step kind",
        tag_keys=("kind",),
    )


def compile_counter() -> metrics.Counter:
    return metrics.counter(
        "llm_compile_events",
        "New jit signatures seen by this engine's DecodeFns, by shape key",
        tag_keys=("shape",),
    )


def shape_key(sig: tuple) -> str:
    """Stable label for one (kind, tokens_shape, tables_shape) signature,
    e.g. ``prefill_chunk:4x32:4x8`` — bounded cardinality because shapes
    are drawn from the closed bucket ladders."""
    kind, tok, tbl = sig
    return (
        f"{kind}:{'x'.join(str(d) for d in tok)}:"
        f"{'x'.join(str(d) for d in tbl)}"
    )


class FlightRecorder:
    """Bounded ring of per-step records for post-mortem debugging.

    ``record()`` appends one dict (phase, bucket shape, admission/eviction
    counts, duration, KV utilization — built by the engine under its
    lock); ``dump()`` packages the ring plus the process's event_stats
    into one JSON-safe dict. Dumped on ``EngineDiedError``, watchdog
    timeout, ``shutdown(dump=...)``, ``engine.debug_dump()`` and the
    proxy's ``/debug/llm`` endpoint.
    """

    def __init__(self, capacity: int = 256):
        self.capacity = max(1, int(capacity))
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._steps = 0

    def __len__(self) -> int:
        return len(self._ring)

    def record(self, rec: dict) -> None:
        """O(1): one append; the ring evicts from the far end."""
        self._steps += 1
        rec["step"] = self._steps
        self._ring.append(rec)

    def snapshot(self) -> list[dict]:
        # list(deque) is a GIL-atomic copy — safe without the engine lock
        # (the watchdog dumps while the wedged stepper still holds it)
        return list(self._ring)

    def dump(self, reason: str, extra: dict | None = None) -> dict:
        out = {
            "reason": reason,
            "ts": wall(),
            "pid": os.getpid(),
            "steps_total": self._steps,
            "capacity": self.capacity,
            "steps": self.snapshot(),
            "event_stats": event_stats.snapshot(),
        }
        if extra:
            out.update(extra)
        return out


def dump_dir(explicit: str | None = None) -> str:
    """Where flight-recorder JSON lands: the engine's configured dir, else
    ``RAY_TPU_FLIGHT_DIR``, else ``<tmp>/ray_tpu_flight``."""
    return (
        explicit
        or os.environ.get("RAY_TPU_FLIGHT_DIR")
        or os.path.join(tempfile.gettempdir(), "ray_tpu_flight")
    )


# dump-directory bound: keep the newest N auto-named dumps. Repeated
# engine deaths (e.g. a crash-looping deployment respawning through a
# controller outage) write one dump per death — unbounded, that fills
# the disk the incident responder needs for the postmortem itself.
FLIGHT_KEEP_ENV = "RAY_TPU_FLIGHT_KEEP"
_FLIGHT_KEEP_DEFAULT = 20


def _prune_dumps(d: str) -> None:
    """Rotate auto-named flight dumps in ``d``: keep the newest N
    (RAY_TPU_FLIGHT_KEEP, default 20; <= 0 disables rotation).
    Best-effort like the writes — pruning must never raise."""
    try:
        keep = int(os.environ.get(FLIGHT_KEEP_ENV, _FLIGHT_KEEP_DEFAULT))
    except ValueError:
        keep = _FLIGHT_KEEP_DEFAULT
    if keep <= 0:
        return
    try:
        names = [
            n
            for n in os.listdir(d)
            if n.startswith("llm_flight_") and n.endswith(".json")
        ]
        if len(names) <= keep:
            return
        # auto-generated names embed wall-clock ms, but concurrent pids
        # interleave — mtime is the honest recency order
        paths = sorted(
            (os.path.join(d, n) for n in names),
            key=lambda p: os.stat(p).st_mtime,
        )
        for p in paths[:-keep]:
            os.unlink(p)
    except OSError as e:
        logger.warning("flight-recorder dir prune failed: %r", e)


def write_dump(
    dump: dict, *, dir: str | None = None, path: str | None = None
) -> str | None:
    """Serialize one flight-recorder dump to disk. Best-effort by
    contract: the dump happens while the engine is dying, and
    observability must never turn a clean failure fan-out into a crash —
    returns the path, or None when the write failed. Auto-named dumps
    rotate (newest RAY_TPU_FLIGHT_KEEP kept); an explicit ``path`` is
    the caller's to manage."""
    auto = path is None
    try:
        if path is None:
            d = dump_dir(dir)
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d,
                f"llm_flight_{os.getpid()}_{int(wall() * 1000)}.json",
            )
        with open(path, "w") as f:
            json.dump(dump, f, indent=1, default=str)
        if auto:
            _prune_dumps(os.path.dirname(path))
        return path
    except Exception as e:  # noqa: BLE001 — never fail the failure path
        logger.warning("flight-recorder dump failed: %r", e)
        return None
