"""Jitted incremental forwards per model family + compile-cache tracking.

One `DecodeFns` per engine: it binds the (static) model config into the
family's prefill / decode-step functions (models/gpt.py, models/llama.py),
jits them once, and records every distinct input-shape signature it is
called with. Because jit caches by shape, the signature set size IS the
number of compiled programs — the engine exposes it so tests (and ops
dashboards) can assert the bucketing keeps it bounded.
"""
from __future__ import annotations

import functools
from typing import Callable


def _gpt_fns(model_cfg):
    from ray_tpu.models.gpt import (
        gpt_decode_step,
        gpt_init,
        gpt_prefill,
        gpt_verify_step,
    )

    return gpt_init, gpt_prefill, gpt_decode_step, gpt_verify_step


def _llama_fns(model_cfg):
    from ray_tpu.models.llama import (
        llama_decode_step,
        llama_init,
        llama_prefill,
        llama_verify_step,
    )

    return llama_init, llama_prefill, llama_decode_step, llama_verify_step


FAMILIES: dict[str, Callable] = {"gpt": _gpt_fns, "llama": _llama_fns}


def family_param_axes(family: str, model_cfg):
    """Logical-axis tree matching the family's init output — what a
    sharded executor feeds parallel.sharding.shard_params. Kept next to
    FAMILIES so adding a model family means extending exactly one
    registry module."""
    if family == "gpt":
        from ray_tpu.models.gpt import gpt_param_axes

        return gpt_param_axes(model_cfg)
    if family == "llama":
        from ray_tpu.models.llama import llama_param_axes

        return llama_param_axes(model_cfg)
    raise ValueError(
        f"unknown model family {family!r}; expected one of "
        f"{sorted(FAMILIES)}"
    )


def family_quant_axes(family: str, model_cfg):
    """Per-leaf amax reduction-axis tree matching the family's init
    output — what the executor feeds ops/quantization.quantize_params
    when ``model_cfg.quantization`` is set (-1 leaves stay f32). Lives
    here for the same reason as family_param_axes."""
    if family == "gpt":
        from ray_tpu.models.gpt import gpt_quant_axes

        return gpt_quant_axes(model_cfg)
    if family == "llama":
        from ray_tpu.models.llama import llama_quant_axes

        return llama_quant_axes(model_cfg)
    raise ValueError(
        f"unknown model family {family!r}; expected one of "
        f"{sorted(FAMILIES)}"
    )

# Process-wide jit cache: jax.jit memoizes traces per *wrapper*, so two
# engines over the same (family, config) — e.g. several replicas colocated
# in one worker, or a test suite constructing many engines — must share
# one wrapper each for prefill/decode or every engine re-compiles every
# bucket shape from scratch. Configs are frozen dataclasses => hashable.
_jit_cache: dict[tuple, tuple] = {}


def _jitted(family: str, model_cfg):
    key = (family, model_cfg)
    hit = _jit_cache.get(key)
    if hit is None:
        import jax

        init, prefill_fn, decode_fn, verify_fn = FAMILIES[family](model_cfg)
        hit = (
            init,
            jax.jit(functools.partial(prefill_fn, cfg=model_cfg)),
            jax.jit(functools.partial(decode_fn, cfg=model_cfg)),
            jax.jit(functools.partial(verify_fn, cfg=model_cfg)),
        )
        _jit_cache[key] = hit
    return hit


class DecodeFns:
    """prefill(params, cache_k, cache_v, tokens, lengths, block_tables)
    and decode(params, cache_k, cache_v, tokens, positions, block_tables),
    jitted with the model config closed over as a static value. Compiled
    programs are shared process-wide per (family, config); the signature
    set below is per-instance, so each engine reports the shapes IT
    exercised."""

    def __init__(self, family: str, model_cfg):
        if family not in FAMILIES:
            raise ValueError(
                f"unknown model family {family!r}; expected one of "
                f"{sorted(FAMILIES)}"
            )
        self.family = family
        self.model_cfg = model_cfg
        self.init, self._prefill, self._decode, self._verify = _jitted(
            family, model_cfg
        )
        self._signatures: set[tuple] = set()
        # called with (kind, tokens_shape, tables_shape) the first time
        # THIS instance sees a signature — the engine hangs its
        # compile-event counter here (jitted programs are process-shared,
        # so per-instance first-use is the per-engine compile event)
        self.on_new_signature = None

    def _note(self, sig: tuple) -> None:
        if sig not in self._signatures:
            self._signatures.add(sig)
            if self.on_new_signature is not None:
                self.on_new_signature(sig)

    def prefill(
        self, params, cache_k, cache_v, tokens, lengths, block_tables,
        start=None, sample=None,
    ):
        # start=None is the monolithic whole-prompt path (positions are
        # arange over the chunk, reference-attention formulation); a [B]
        # start array is the chunked/prefix path (true positions, paged
        # attention over already-resident context). The two trace to
        # different programs, so they get distinct signature kinds.
        # ``sample`` (a pytree of [B] arrays, ops/sampling.py) fuses
        # sampling into the SAME kind — it swaps the program's epilogue
        # (token ids out instead of logits), not its signature, so the
        # compile-count contract stays (prefill, prefill_chunk, decode)
        # x batch_buckets x length_buckets.
        kind = "prefill" if start is None else "prefill_chunk"
        self._note(
            (kind, tuple(tokens.shape), tuple(block_tables.shape))
        )
        if start is None:
            return self._prefill(
                params, cache_k, cache_v, tokens, lengths, block_tables,
                sample=sample,
            )
        return self._prefill(
            params, cache_k, cache_v, tokens, lengths, block_tables,
            start=start, sample=sample,
        )

    def decode(self, params, cache_k, cache_v, tokens, positions,
               block_tables, sample=None):
        self._note(
            ("decode", tuple(tokens.shape), tuple(block_tables.shape))
        )
        return self._decode(
            params, cache_k, cache_v, tokens, positions, block_tables,
            sample=sample,
        )

    def verify(self, params, cache_k, cache_v, tokens, starts, draft_len,
               block_tables, sample=None):
        # speculative-decoding verify window: tokens [B, W] with W fixed
        # per engine at speculative_k + 1 (per-row draft availability is
        # DATA — draft_len — not shape), so the signature set adds exactly
        # ("verify",) x batch_buckets x tables-width and stays frozen
        # under mixed speculative/plain traffic.
        self._note(
            ("verify", tuple(tokens.shape), tuple(block_tables.shape))
        )
        return self._verify(
            params, cache_k, cache_v, tokens, starts, draft_len,
            block_tables, sample=sample,
        )

    @property
    def num_compiled_shapes(self) -> int:
        """Distinct (kind, shape) signatures seen — each is one XLA
        compile. The bucketed scheduler keeps this at
        O(|batch_buckets| * |length_buckets|) regardless of traffic."""
        return len(self._signatures)

    @property
    def signatures(self) -> frozenset:
        return frozenset(self._signatures)
