"""Host-side draft proposal for speculative decoding.

A ``Drafter`` proposes up to k candidate next tokens for a sequence from
host-visible state alone (prompt + committed tokens as plain Python ints);
the engine packs the proposals into a [B, k+1] verify window that the
target model scores in ONE jitted call (``verify_step`` on the executor),
and the on-device ``verify_tokens`` epilogue (ops/sampling.py) accepts a
prefix of them plus one corrected token. The drafter is pure scheduling
input: a wrong draft costs only wasted verify FLOPs, never correctness —
acceptance is exact-match against the keyed sampler, so committed streams
are byte-identical to non-speculative decoding whatever the drafter says.

This module is deliberately device-free AND numpy-free: it runs on the
scheduler's host thread between steps, holds zero device memory, and the
host-sync AST lint in tests/test_sanitizers.py covers it so speculation
can never quietly introduce a second device->host sync. A learned draft
MODEL can implement the same ``propose`` contract later (it would run its
own small executor and sync through the one blessed ``_host_tokens``
channel); the engine only depends on the interface below.

``NGramDrafter`` is the model-free default: prompt-lookup decoding
(Saxena; also vLLM's ngram speculator) — find the most recent earlier
occurrence of the current n-gram suffix in prompt + generated and propose
its continuation. It shines exactly where one-token-per-step decode hurts
most: repeated structure (code, templated text, greedy repetition loops),
where long continuations verify successfully and a step commits several
tokens at once.
"""
from __future__ import annotations

from typing import Protocol, Sequence, runtime_checkable


@runtime_checkable
class Drafter(Protocol):
    """Proposes candidate continuation tokens for one sequence."""

    def propose(
        self, prompt: Sequence[int], generated: Sequence[int], k: int
    ) -> list[int]:
        """Return 0..k draft token ids expected to follow
        ``prompt + generated``. Fewer than k (including none) is always
        legal — the engine clamps per-row draft length to what the step
        budget allows anyway. Must not touch device values."""
        ...


class NGramDrafter:
    """Prompt-lookup drafter: match the longest recent suffix n-gram
    (``max_n`` down to ``min_n`` tokens) against earlier context and
    propose the tokens that followed its most recent occurrence."""

    def __init__(self, max_n: int = 4, min_n: int = 1):
        if min_n < 1 or max_n < min_n:
            raise ValueError(
                f"need 1 <= min_n <= max_n, got min_n={min_n} max_n={max_n}"
            )
        self.max_n = max_n
        self.min_n = min_n

    def propose(
        self, prompt: Sequence[int], generated: Sequence[int], k: int
    ) -> list[int]:
        if k <= 0:
            return []
        ctx = list(prompt) + list(generated)
        L = len(ctx)
        for n in range(min(self.max_n, L - 1), self.min_n - 1, -1):
            pattern = ctx[L - n:]
            # most recent earlier occurrence wins: recent context is the
            # best predictor when generation has entered a repeating cycle
            for i in range(L - n - 1, -1, -1):
                if ctx[i:i + n] == pattern:
                    return ctx[i + n:i + n + k]
        return []


def build_drafter(spec) -> Drafter | None:
    """EngineConfig.drafter -> Drafter instance. Accepts None (no drafts:
    every speculative step degenerates to draft_len 0), the string
    "ngram", or any object with a ``propose`` method (duck-typed so tests
    can inject oracles/adversaries)."""
    if spec is None:
        return None
    if isinstance(spec, str):
        if spec == "ngram":
            return NGramDrafter()
        raise ValueError(
            f"unknown drafter {spec!r}; expected 'ngram', None, or a "
            "Drafter instance"
        )
    if not hasattr(spec, "propose"):
        raise TypeError(
            f"drafter {spec!r} does not implement Drafter.propose"
        )
    return spec
