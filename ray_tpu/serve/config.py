"""Serve configuration dataclasses.

Equivalent of the reference's Serve config surface
(reference: python/ray/serve/config.py — DeploymentConfig/AutoscalingConfig;
python/ray/serve/schema.py pydantic schemas). Plain dataclasses here: the
validation surface is small and pydantic is not load-bearing for behavior.
"""
from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class AutoscalingConfig:
    """Replica autoscaling targets
    (reference: serve/config.py AutoscalingConfig; policy math in
    serve/_private/autoscaling_policy.py:12 calculate_desired_num_replicas).
    """

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    upscale_smoothing_factor: float = 1.0
    downscale_smoothing_factor: float = 0.5
    # consecutive decisions required before acting (reference: upscale_delay_s/
    # downscale_delay_s expressed in loop periods)
    upscale_delay_periods: int = 1
    downscale_delay_periods: int = 3

    # --- engine-signal thresholds (serve.llm AutoscalingSnapshot) ---
    # A replica is HOT (scale up) when any of these trip; the fleet scales
    # DOWN only when every replica is cold (no queued or running work and
    # KV pressure below the downscale bound). Pressures are fractions of
    # the usable KV pool in [0, 1].
    upscale_queue_wait_p95_s: float = 0.25
    upscale_kv_pressure: float = 0.85
    # deadline misses per second above which a replica counts as hot; the
    # default 0.0 means "any miss is a saturation signal"
    upscale_deadline_miss_rate: float = 0.0
    downscale_kv_pressure: float = 0.5
    # snapshots older than this (on obs.clock) are ignored by aggregation
    signal_ttl_s: float = 5.0
    # Which saturation signals count toward HOT (disaggregated
    # prefill/decode pools scale on disjoint signals):
    #   "all"     — every threshold (the default, single-pool behavior)
    #   "prefill" — admission-side only: queue-wait p95 + rejections
    #               (the prefill pool's TTFT story)
    #   "decode"  — generation-side only: KV pressure + deadline misses
    #               + optionally decode-step p50 (the TPOT story)
    # Coldness (scale-down) is mode-independent: idle is idle.
    signal_mode: str = "all"
    # decode-step p50 (seconds) above which a "decode"/"all"-mode replica
    # counts as hot; None disables the check (pressure/misses only)
    upscale_decode_step_p50_s: float | None = None

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"need 0 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}/{self.max_replicas}"
            )
        for name in ("upscale_kv_pressure", "downscale_kv_pressure"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {v}")
        if self.upscale_queue_wait_p95_s < 0 or self.upscale_deadline_miss_rate < 0:
            raise ValueError("signal thresholds must be >= 0")
        if self.signal_mode not in ("all", "prefill", "decode"):
            raise ValueError(
                "signal_mode must be 'all', 'prefill', or 'decode', got "
                f"{self.signal_mode!r}"
            )
        if (self.upscale_decode_step_p50_s is not None
                and self.upscale_decode_step_p50_s <= 0):
            raise ValueError(
                "upscale_decode_step_p50_s must be positive or None, got "
                f"{self.upscale_decode_step_p50_s}"
            )


@dataclass
class BatchConfig:
    """Router-side dynamic batching for one replica method.

    TPU-first deviation from the reference: the reference batches inside the
    replica's asyncio loop (serve/batching.py:337) with arbitrary resulting
    batch sizes; here the router coalesces and can pad to fixed bucket sizes
    so a jitted model never sees a new shape (XLA recompile avoidance —
    SURVEY.md §7 "the router/batcher must be shape-aware").
    """

    max_batch_size: int = 8
    batch_wait_timeout_s: float = 0.01
    # optional ascending bucket sizes; router pads submitted batch lists to
    # the next bucket with `None` entries which the replica wrapper strips
    # after the model call (shape-stable submission)
    size_buckets: tuple[int, ...] | None = None


@dataclass(frozen=True)
class ModelParallelConfig:
    """Per-replica model-parallel layout for LLM serving
    (serve/llm/executor.py ShardedExecutor).

    ``tp`` shards attention/KV heads, MLP hidden, and the vocab
    projection Megatron-style — including the paged KV pool, which
    splits along its head axis (so ``n_kv_head % tp == 0`` is required);
    ``fsdp`` shards the embed axis of every weight (ZeRO-3). One replica
    occupies ``tp * fsdp`` chips; the default (1, 1) keeps the
    single-device executor and changes nothing. Passed as the ``mesh``
    field of ``EngineConfig`` (or via ``LLMDeployment`` /
    ``build_llm_app`` plumbing).

    ``attention_backend`` selects the decode attention kernel for the
    replica (None -> the engine/model default; "auto" | "xla" |
    "pallas" — ops/paged_attention.py). The Pallas kernel is
    head-count-agnostic, so it runs per tp shard over the pool's local
    KV heads with no extra collective.
    """

    tp: int = 1
    fsdp: int = 1
    attention_backend: str | None = None

    def __post_init__(self):
        if self.tp < 1 or self.fsdp < 1:
            raise ValueError(
                f"tp and fsdp must be >= 1, got tp={self.tp} "
                f"fsdp={self.fsdp}"
            )
        if self.attention_backend not in (None, "auto", "xla", "pallas"):
            raise ValueError(
                "attention_backend must be None, 'auto', 'xla', or "
                f"'pallas', got {self.attention_backend!r}"
            )

    @property
    def n_devices(self) -> int:
        return self.tp * self.fsdp


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    autoscaling_config: AutoscalingConfig | None = None
    # actor resources per replica (TPU chips ride here: {"TPU": 1})
    ray_actor_options: dict = field(default_factory=dict)
    health_check_period_s: float = 1.0
    graceful_shutdown_timeout_s: float = 5.0
    user_config: dict | None = None
    # Disaggregated serving role tag ("prefill" | "decode" | None).
    # Purely observational — the controller keys the
    # llm_prefill_pool_replicas gauge off it; routing/scaling behavior
    # comes from the deployment's own autoscaling_config.signal_mode.
    pool_role: str | None = None

    def __post_init__(self):
        if self.pool_role not in (None, "prefill", "decode"):
            raise ValueError(
                "pool_role must be None, 'prefill', or 'decode', got "
                f"{self.pool_role!r}"
            )

    @property
    def target_num_replicas(self) -> int:
        if self.autoscaling_config is not None:
            return self.autoscaling_config.min_replicas
        return self.num_replicas


@dataclass
class HTTPOptions:
    """HTTP ingress options (reference: serve/config.py HTTPOptions —
    including request_timeout_s). port=0 binds an ephemeral port (exposed
    as HTTPProxy.port / serve.proxy_addresses())."""

    host: str = "127.0.0.1"
    port: int = 8000
    # end-to-end budget for a unary result and the per-chunk budget for
    # streamed responses; None waits forever
    request_timeout_s: float | None = 120.0
    # head sampling for the fleet trace plane: fraction of UNTAGGED
    # requests (no x-ray-tpu-trace header) the proxy traces anyway, so
    # production traffic feeds the TraceStore without client opt-in.
    # Sampled per request from the proxy's seeded RNG; 0.0 = header-only.
    trace_sample_rate: float = 0.0


@dataclass
class GrpcOptions:
    """gRPC ingress (reference: serve gRPCOptions — grpc_servicer_functions
    replaced by the generic byte-payload ServeAPI service, grpc_proxy.py).
    port=0 binds an ephemeral port (exposed as GrpcProxy.port)."""

    host: str = "127.0.0.1"
    port: int = 9000
    request_timeout_s: float | None = 120.0
    # head sampling, same semantics as HTTPOptions.trace_sample_rate
    trace_sample_rate: float = 0.0
