"""HTTP ingress proxy (aiohttp) routing to deployment handles.

Equivalent of the reference's per-node HTTPProxy
(reference: python/ray/serve/_private/proxy.py:896,975 uvicorn ASGI proxy,
proxy_request :364 → Router.assign_replica). Ours is an aiohttp server in a
daemon thread; request JSON bodies become the single call payload and
handler results are returned as JSON.
"""
from __future__ import annotations

import asyncio
import contextlib
import itertools
import json
import logging
import random
import threading
import time
import uuid
import zlib
from typing import Any

import ray_tpu
from ray_tpu._private import event_stats
from ray_tpu.exceptions import (
    DeadlineExceededError,
    EngineOverloadedError,
    RequestCancelledError,
    TaskError,
)
from ray_tpu.serve.config import HTTPOptions
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponseGenerator
from ray_tpu.util import tracing

# Structured access logs (one JSON object per line) shared by the HTTP and
# gRPC proxies — docs/OBSERVABILITY.md "Access logs".
_access_logger = logging.getLogger("ray_tpu.serve.access")

# Request header (HTTP) / metadata key (gRPC) that opts a call into
# tracing; the assigned trace id is echoed back on this response header.
TRACE_HEADER = "x-ray-tpu-trace"
TRACE_ID_HEADER = "x-ray-tpu-trace-id"

# Request header (HTTP) / metadata key (gRPC) naming the LLM scheduling
# class ("interactive" | "default" | "batch"); injected into dict payloads
# as ``priority`` (docs/SERVING_LLM.md "Priority & preemption").
PRIORITY_HEADER = "x-ray-tpu-priority"

# Class-aware backoff hints: interactive retries fast (capacity opens as
# soon as a stream completes), batch backs off hard (it is the first class
# shed and the last resumed under sustained overload).
_RETRY_AFTER = {"interactive": "1", "default": "2", "batch": "5"}


def head_sampler(seed: str, rate: float):
    """Head-sampling decision for one proxy: trace ``rate`` of requests
    that did NOT opt in via the trace header, so production traffic feeds
    the fleet TraceStore without client cooperation. A closure over a
    seeded RNG (the repo-wide ``random.Random(zlib.crc32(...))`` pattern —
    never the process-global ``random.random()``) so the sampled share is
    deterministic per seed and replayable in tests."""
    rng = random.Random(zlib.crc32(seed.encode()))

    def sample() -> bool:
        if rate <= 0.0:
            return False
        if rate >= 1.0:
            return True
        return rng.random() < rate

    return sample


def log_access(proxy: str, path: str, state: dict, *, status: str,
               error: str | None = None) -> None:
    """Emit one structured access-log line. ``state`` accumulates during
    the request: t0 (perf-counter start), request_id, trace_id, ttft_ms,
    tokens, resumed. Idempotent — streams can hit both the handler's error
    path and the pump's completion path."""
    if state.get("_logged"):
        return
    state["_logged"] = True
    dur = time.perf_counter() - state["t0"] if "t0" in state else 0.0
    event_stats.record(f"serve.proxy.{proxy}.request", dur)
    _access_logger.info(json.dumps({
        "proxy": proxy,
        "path": path,
        "request_id": state.get("request_id"),
        "trace_id": state.get("trace_id"),
        "status": status,
        "ttft_ms": state.get("ttft_ms"),
        "tokens": state.get("tokens", 0),
        "resumed": state.get("resumed", 0),
        "duration_ms": round(dur * 1000.0, 3),
        "error": error,
    }, default=str))


def _unwrap(e: BaseException) -> BaseException:
    if isinstance(e, TaskError) and e.cause is not None:
        return e.cause
    return e


def _status_for(e: BaseException,
                priority: str | None = None) -> tuple[int, dict]:
    """Map framework errors to HTTP degradation statuses: overload is
    retryable (503 + Retry-After, with a class-aware backoff hint and a
    per-priority shed counter — under class-aware shedding batch is
    rejected first, so operators can see WHICH class is degraded), a
    blown deadline is a gateway timeout (504), a cancelled request is
    nginx's client-closed-request (499), and a request-validation
    ValueError — including GrammarError for an invalid or unsatisfiable
    response_format — is the client's fault (400, never a 500/failover).
    """
    from ray_tpu.util import metrics

    e = _unwrap(e)
    if isinstance(e, EngineOverloadedError):
        pc = priority or "default"
        metrics.counter(
            "serve_requests_shed",
            "Requests rejected with an overload status at a proxy, "
            "by priority class",
            tag_keys=("proxy", "priority"),
        ).inc(tags={"proxy": "http", "priority": pc})
        return 503, {"Retry-After": _RETRY_AFTER.get(pc, "2")}
    if isinstance(e, DeadlineExceededError):
        return 504, {}
    if isinstance(e, RequestCancelledError):
        return 499, {}
    if isinstance(e, ValueError):
        return 400, {}
    return 500, {}


class _PrefetchedStream:
    """A streaming response whose FIRST chunk was already fetched on the
    executor thread. Fetching one chunk before building the HTTP response
    means admission-control/deadline errors surface while the status line
    is still unsent — so overload really is a 503, not a 200 + mid-stream
    error chunk."""

    def __init__(self, chunks):
        self.chunks = chunks

    def __iter__(self):
        return iter(self.chunks)


class HTTPProxy:
    def __init__(self, options: HTTPOptions):
        self.options = options
        self._head_sample = head_sampler(
            f"http:{options.host}:{options.port}", options.trace_sample_rate)
        self.port: int | None = None  # bound port (options.port=0 works)
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._runner = None
        self._started = threading.Event()
        self._start_error: Exception | None = None
        # route_prefix -> (app_name, ingress deployment)
        self._routes: dict[str, tuple[str, str]] = {}
        self._routes_lock = threading.Lock()

    # -- route table --

    def set_route(self, route_prefix: str, app_name: str, ingress: str) -> None:
        with self._routes_lock:
            self._routes[route_prefix.rstrip("/") or "/"] = (app_name, ingress)

    def replace_routes(self, routes: dict[str, tuple[str, str]]) -> None:
        """Swap in a full route table (proxy-actor route sync)."""
        with self._routes_lock:
            self._routes = {
                (k.rstrip("/") or "/"): tuple(v) for k, v in routes.items()
            }

    def remove_routes_for_app(self, app_name: str) -> None:
        with self._routes_lock:
            self._routes = {
                k: v for k, v in self._routes.items() if v[0] != app_name
            }

    def _match(self, path: str) -> tuple[str, str] | None:
        with self._routes_lock:
            best = None
            for prefix, target in self._routes.items():
                if path == prefix or path.startswith(prefix.rstrip("/") + "/") or prefix == "/":
                    if best is None or len(prefix) > len(best[0]):
                        best = (prefix, target)
            return best[1] if best else None

    # -- server --

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._serve_thread, daemon=True, name="serve-http-proxy"
        )
        self._thread.start()
        if not self._started.wait(15):
            raise RuntimeError("HTTP proxy failed to start in time")
        if self._start_error is not None:
            raise self._start_error

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)

    def _serve_thread(self) -> None:
        from aiohttp import web

        _END = object()

        def _encode_chunk(chunk: Any, sse: bool) -> bytes:
            if sse:
                if isinstance(chunk, bytes):
                    body = chunk.decode(errors="replace")
                elif isinstance(chunk, str):
                    body = chunk
                else:
                    body = json.dumps(chunk)
                return b"data: " + body.encode() + b"\n\n"
            if isinstance(chunk, bytes):
                return chunk
            if isinstance(chunk, str):
                return chunk.encode()
            return json.dumps(chunk).encode() + b"\n"

        async def stream_response(request, response_gen,
                                  on_disconnect=None,
                                  headers=None) -> "web.StreamResponse":
            """Pump chunks from the blocking DeploymentResponseGenerator
            (iterated on an executor thread) out the socket as they arrive
            — token streaming for LLM decode (reference:
            serve/_private/proxy.py streaming ASGI responses). Server-sent
            events when the client asks for text/event-stream; raw chunked
            transfer otherwise."""
            sse = "text/event-stream" in request.headers.get("Accept", "")
            resp = web.StreamResponse()
            if headers:
                resp.headers.update(headers)
            resp.content_type = ("text/event-stream" if sse
                                 else "application/octet-stream")
            resp.enable_chunked_encoding()
            await resp.prepare(request)
            loop = asyncio.get_event_loop()
            queue: asyncio.Queue = asyncio.Queue(maxsize=16)

            timeout_s = self.options.request_timeout_s

            def pump():
                try:
                    for chunk in response_gen:
                        f = asyncio.run_coroutine_threadsafe(
                            queue.put(chunk), loop)
                        f.result(timeout=timeout_s)
                    asyncio.run_coroutine_threadsafe(
                        queue.put(_END), loop).result(timeout=timeout_s)
                except BaseException as e:  # noqa: BLE001 — ship to client
                    try:
                        asyncio.run_coroutine_threadsafe(
                            queue.put(e), loop).result(timeout=timeout_s)
                    except Exception:
                        pass

            threading.Thread(target=pump, daemon=True,
                             name="serve-stream-pump").start()
            try:
                while True:
                    item = await queue.get()
                    if item is _END:
                        break
                    if isinstance(item, BaseException):
                        await resp.write(_encode_chunk(
                            {"error": str(item)}, sse))
                        break
                    await resp.write(_encode_chunk(item, sse))
                await resp.write_eof()
            except (ConnectionResetError, ConnectionError,
                    asyncio.CancelledError):
                # client went away mid-stream: free the replica-side
                # sequence (and its KV blocks) instead of decoding into
                # the void until max_new_tokens
                if on_disconnect is not None:
                    on_disconnect()
                raise
            return resp

        async def debug_llm(request: web.Request) -> web.Response:
            """GET /debug/llm?app=<name>: broadcast ``debug_dump()`` to
            every replica of the app's ingress deployment — flight-recorder
            snapshot + scheduler/cache stats per replica, as JSON (None
            where a replica failed or lacks the method)."""
            app_name = request.query.get("app", "default")
            with self._routes_lock:
                apps = {a: ing for (a, ing) in self._routes.values()}
            ingress = apps.get(app_name)
            if ingress is None:
                return web.json_response(
                    {"error": f"unknown app {app_name!r}",
                     "apps": sorted(apps)},
                    status=404,
                )

            def dump_blocking():
                return DeploymentHandle(ingress, app_name).broadcast(
                    "debug_dump")

            try:
                dumps = await asyncio.get_event_loop().run_in_executor(
                    None, dump_blocking
                )
            except Exception as e:  # noqa: BLE001 — surface to the client
                return web.json_response({"error": str(e)}, status=500)
            return web.json_response(
                {"app": app_name, "replicas": dumps},
                dumps=lambda o: json.dumps(o, default=str),
            )

        async def handler(request: web.Request) -> web.Response:
            if request.path == "/healthz":
                # controller-INDEPENDENT readiness: answers from purely
                # local state, so load balancers keep this proxy in
                # rotation through a controller outage (routing keeps
                # working from cached tables; see handle._Router._refresh)
                with self._routes_lock:
                    n_routes = len(self._routes)
                return web.json_response(
                    {"status": "ok", "routes": n_routes}
                )
            if request.path == "/debug/llm":
                return await debug_llm(request)
            target = self._match(request.path)
            if target is None:
                return web.json_response(
                    {"error": f"no app routes {request.path}"}, status=404
                )
            app_name, ingress = target
            if request.can_read_body:
                raw = await request.read()
                try:
                    payload: Any = json.loads(raw) if raw else None
                except json.JSONDecodeError:
                    payload = raw.decode()
            else:
                payload = dict(request.query) or None
            # The whole call (routing included) runs in the executor: the
            # router does blocking controller RPCs and may sleep waiting for
            # replicas, which must never stall the event loop. For generator
            # ingresses the first chunk is ALSO fetched there, so admission
            # and deadline errors map to a status code before the response
            # headers go out; remaining chunks are pumped by stream_response.
            traced = TRACE_HEADER in request.headers or self._head_sample()
            prio_header = request.headers.get(PRIORITY_HEADER)
            state: dict[str, Any] = {"t0": time.perf_counter()}

            def call_blocking():
                nonlocal payload
                # run_in_executor does NOT propagate contextvars, so the
                # root span must open HERE on the executor thread — the
                # dispatch below captures trace_ctx from it into the spec
                root = (
                    tracing.span("http.request", path=request.path,
                                 method=request.method)
                    if traced else contextlib.nullcontext({})
                )
                with root as ctx:
                    if ctx.get("trace_id"):
                        state["trace_id"] = ctx["trace_id"]
                    handle = DeploymentHandle(ingress, app_name).options(
                        stream_chunk_timeout_s=self.options.request_timeout_s)
                    if isinstance(payload, dict):
                        try:
                            streaming_ingress = (
                                "__call__" in handle.stream_methods())
                        except Exception:  # noqa: BLE001 — best-effort tag
                            streaming_ingress = False
                        if streaming_ingress:
                            # tag the request so a client disconnect can
                            # cancel it on whichever replica is serving it
                            payload = dict(payload)
                            payload.setdefault("request_id", uuid.uuid4().hex)
                            # priority class rides the header (payload key
                            # wins); class-aware shedding and per-class
                            # overload accounting key on it
                            if prio_header:
                                payload.setdefault("priority", prio_header)
                            state["request_id"] = payload["request_id"]
                            state["handle"] = handle
                        if payload.get("priority"):
                            state["priority"] = str(payload["priority"])
                    response = handle.remote(payload)
                    if isinstance(response, DeploymentResponseGenerator):
                        it = iter(response)
                        try:
                            first = next(it)
                        except StopIteration:
                            return _PrefetchedStream(())
                        state["ttft_ms"] = round(
                            (time.perf_counter() - state["t0"]) * 1000.0, 3)
                        return _PrefetchedStream(itertools.chain([first], it))
                    return response.result(
                        timeout=self.options.request_timeout_s)

            try:
                result = await asyncio.get_event_loop().run_in_executor(
                    None, call_blocking
                )
            except Exception as e:  # noqa: BLE001 — surface to the client
                status, headers = _status_for(e, state.get("priority"))
                log_access("http", request.path, state,
                           status=str(status), error=str(e))
                return web.json_response(
                    {"error": str(e)}, status=status, headers=headers)
            trace_headers = ({TRACE_ID_HEADER: state["trace_id"]}
                             if "trace_id" in state else None)
            if isinstance(result, _PrefetchedStream):
                def on_disconnect():
                    log_access("http", request.path, state,
                               status="disconnect")
                    rid = state.get("request_id")
                    handle = state.get("handle")
                    if rid is None or handle is None:
                        return
                    threading.Thread(
                        target=lambda: handle.broadcast("cancel", rid),
                        daemon=True, name="serve-cancel",
                    ).start()

                def counted(chunks):
                    # runs on the pump thread: count chunks out and emit
                    # the access-log line when the stream actually ends
                    try:
                        for c in chunks:
                            state["tokens"] = state.get("tokens", 0) + 1
                            yield c
                    except BaseException as e:
                        log_access("http", request.path, state,
                                   status="error", error=str(e))
                        raise
                    log_access("http", request.path, state, status="200")

                return await stream_response(
                    request, _PrefetchedStream(counted(result.chunks)),
                    on_disconnect, headers=trace_headers)
            log_access("http", request.path, state, status="200")
            if isinstance(result, (dict, list, str, int, float, bool, type(None))):
                return web.json_response({"result": result},
                                         headers=trace_headers)
            return web.json_response({"result": repr(result)},
                                     headers=trace_headers)

        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handler)
        runner = web.AppRunner(app)
        try:
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, self.options.host, self.options.port)
            loop.run_until_complete(site.start())
            self.port = site._server.sockets[0].getsockname()[1]
        except Exception as e:  # noqa: BLE001 — report to starter
            self._start_error = e
            self._started.set()
            return
        self._runner = runner
        self._started.set()
        loop.run_forever()
        loop.run_until_complete(runner.cleanup())
