"""HTTP ingress proxy (aiohttp) routing to deployment handles.

Equivalent of the reference's per-node HTTPProxy
(reference: python/ray/serve/_private/proxy.py:896,975 uvicorn ASGI proxy,
proxy_request :364 → Router.assign_replica). Ours is an aiohttp server in a
daemon thread; request JSON bodies become the single call payload and
handler results are returned as JSON.
"""
from __future__ import annotations

import asyncio
import json
import threading
from typing import Any

import ray_tpu
from ray_tpu.serve.config import HTTPOptions
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponseGenerator


class HTTPProxy:
    def __init__(self, options: HTTPOptions):
        self.options = options
        self.port: int | None = None  # bound port (options.port=0 works)
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._runner = None
        self._started = threading.Event()
        self._start_error: Exception | None = None
        # route_prefix -> (app_name, ingress deployment)
        self._routes: dict[str, tuple[str, str]] = {}
        self._routes_lock = threading.Lock()

    # -- route table --

    def set_route(self, route_prefix: str, app_name: str, ingress: str) -> None:
        with self._routes_lock:
            self._routes[route_prefix.rstrip("/") or "/"] = (app_name, ingress)

    def replace_routes(self, routes: dict[str, tuple[str, str]]) -> None:
        """Swap in a full route table (proxy-actor route sync)."""
        with self._routes_lock:
            self._routes = {
                (k.rstrip("/") or "/"): tuple(v) for k, v in routes.items()
            }

    def remove_routes_for_app(self, app_name: str) -> None:
        with self._routes_lock:
            self._routes = {
                k: v for k, v in self._routes.items() if v[0] != app_name
            }

    def _match(self, path: str) -> tuple[str, str] | None:
        with self._routes_lock:
            best = None
            for prefix, target in self._routes.items():
                if path == prefix or path.startswith(prefix.rstrip("/") + "/") or prefix == "/":
                    if best is None or len(prefix) > len(best[0]):
                        best = (prefix, target)
            return best[1] if best else None

    # -- server --

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._serve_thread, daemon=True, name="serve-http-proxy"
        )
        self._thread.start()
        if not self._started.wait(15):
            raise RuntimeError("HTTP proxy failed to start in time")
        if self._start_error is not None:
            raise self._start_error

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)

    def _serve_thread(self) -> None:
        from aiohttp import web

        _END = object()

        def _encode_chunk(chunk: Any, sse: bool) -> bytes:
            if sse:
                if isinstance(chunk, bytes):
                    body = chunk.decode(errors="replace")
                elif isinstance(chunk, str):
                    body = chunk
                else:
                    body = json.dumps(chunk)
                return b"data: " + body.encode() + b"\n\n"
            if isinstance(chunk, bytes):
                return chunk
            if isinstance(chunk, str):
                return chunk.encode()
            return json.dumps(chunk).encode() + b"\n"

        async def stream_response(request, response_gen) -> "web.StreamResponse":
            """Pump chunks from the blocking DeploymentResponseGenerator
            (iterated on an executor thread) out the socket as they arrive
            — token streaming for LLM decode (reference:
            serve/_private/proxy.py streaming ASGI responses). Server-sent
            events when the client asks for text/event-stream; raw chunked
            transfer otherwise."""
            sse = "text/event-stream" in request.headers.get("Accept", "")
            resp = web.StreamResponse()
            resp.content_type = ("text/event-stream" if sse
                                 else "application/octet-stream")
            resp.enable_chunked_encoding()
            await resp.prepare(request)
            loop = asyncio.get_event_loop()
            queue: asyncio.Queue = asyncio.Queue(maxsize=16)

            timeout_s = self.options.request_timeout_s

            def pump():
                try:
                    for chunk in response_gen:
                        f = asyncio.run_coroutine_threadsafe(
                            queue.put(chunk), loop)
                        f.result(timeout=timeout_s)
                    asyncio.run_coroutine_threadsafe(
                        queue.put(_END), loop).result(timeout=timeout_s)
                except BaseException as e:  # noqa: BLE001 — ship to client
                    try:
                        asyncio.run_coroutine_threadsafe(
                            queue.put(e), loop).result(timeout=timeout_s)
                    except Exception:
                        pass

            threading.Thread(target=pump, daemon=True,
                             name="serve-stream-pump").start()
            while True:
                item = await queue.get()
                if item is _END:
                    break
                if isinstance(item, BaseException):
                    await resp.write(_encode_chunk(
                        {"error": str(item)}, sse))
                    break
                await resp.write(_encode_chunk(item, sse))
            await resp.write_eof()
            return resp

        async def handler(request: web.Request) -> web.Response:
            target = self._match(request.path)
            if target is None:
                return web.json_response(
                    {"error": f"no app routes {request.path}"}, status=404
                )
            app_name, ingress = target
            if request.can_read_body:
                raw = await request.read()
                try:
                    payload: Any = json.loads(raw) if raw else None
                except json.JSONDecodeError:
                    payload = raw.decode()
            else:
                payload = dict(request.query) or None
            # The whole call (routing included) runs in the executor: the
            # router does blocking controller RPCs and may sleep waiting for
            # replicas, which must never stall the event loop. For generator
            # ingresses the handle returns a response GENERATOR immediately
            # (dispatch is non-blocking); chunks are pumped by stream_response.
            def call_blocking():
                handle = DeploymentHandle(ingress, app_name).options(
                    stream_chunk_timeout_s=self.options.request_timeout_s)
                response = handle.remote(payload)
                if isinstance(response, DeploymentResponseGenerator):
                    return response
                return response.result(
                    timeout=self.options.request_timeout_s)

            try:
                result = await asyncio.get_event_loop().run_in_executor(
                    None, call_blocking
                )
            except Exception as e:  # noqa: BLE001 — surface to the client
                return web.json_response({"error": str(e)}, status=500)
            if isinstance(result, DeploymentResponseGenerator):
                return await stream_response(request, result)
            if isinstance(result, (dict, list, str, int, float, bool, type(None))):
                return web.json_response({"result": result})
            return web.json_response({"result": repr(result)})

        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handler)
        runner = web.AppRunner(app)
        try:
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, self.options.host, self.options.port)
            loop.run_until_complete(site.start())
            self.port = site._server.sockets[0].getsockname()[1]
        except Exception as e:  # noqa: BLE001 — report to starter
            self._start_error = e
            self._started.set()
            return
        self._runner = runner
        self._started.set()
        loop.run_forever()
        loop.run_until_complete(runner.cleanup())
