"""HTTP ingress proxy (aiohttp) routing to deployment handles.

Equivalent of the reference's per-node HTTPProxy
(reference: python/ray/serve/_private/proxy.py:896,975 uvicorn ASGI proxy,
proxy_request :364 → Router.assign_replica). Ours is an aiohttp server in a
daemon thread; request JSON bodies become the single call payload and
handler results are returned as JSON.
"""
from __future__ import annotations

import asyncio
import json
import threading
from typing import Any

import ray_tpu
from ray_tpu.serve.config import HTTPOptions
from ray_tpu.serve.handle import DeploymentHandle


class HTTPProxy:
    def __init__(self, options: HTTPOptions):
        self.options = options
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._runner = None
        self._started = threading.Event()
        self._start_error: Exception | None = None
        # route_prefix -> (app_name, ingress deployment)
        self._routes: dict[str, tuple[str, str]] = {}
        self._routes_lock = threading.Lock()

    # -- route table --

    def set_route(self, route_prefix: str, app_name: str, ingress: str) -> None:
        with self._routes_lock:
            self._routes[route_prefix.rstrip("/") or "/"] = (app_name, ingress)

    def remove_routes_for_app(self, app_name: str) -> None:
        with self._routes_lock:
            self._routes = {
                k: v for k, v in self._routes.items() if v[0] != app_name
            }

    def _match(self, path: str) -> tuple[str, str] | None:
        with self._routes_lock:
            best = None
            for prefix, target in self._routes.items():
                if path == prefix or path.startswith(prefix.rstrip("/") + "/") or prefix == "/":
                    if best is None or len(prefix) > len(best[0]):
                        best = (prefix, target)
            return best[1] if best else None

    # -- server --

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._serve_thread, daemon=True, name="serve-http-proxy"
        )
        self._thread.start()
        if not self._started.wait(15):
            raise RuntimeError("HTTP proxy failed to start in time")
        if self._start_error is not None:
            raise self._start_error

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)

    def _serve_thread(self) -> None:
        from aiohttp import web

        async def handler(request: web.Request) -> web.Response:
            target = self._match(request.path)
            if target is None:
                return web.json_response(
                    {"error": f"no app routes {request.path}"}, status=404
                )
            app_name, ingress = target
            if request.can_read_body:
                raw = await request.read()
                try:
                    payload: Any = json.loads(raw) if raw else None
                except json.JSONDecodeError:
                    payload = raw.decode()
            else:
                payload = dict(request.query) or None
            # The whole call (routing included) runs in the executor: the
            # router does blocking controller RPCs and may sleep waiting for
            # replicas, which must never stall the event loop.
            def call_blocking():
                handle = DeploymentHandle(ingress, app_name)
                return handle.remote(payload).result(timeout=120)

            try:
                result = await asyncio.get_event_loop().run_in_executor(
                    None, call_blocking
                )
            except Exception as e:  # noqa: BLE001 — surface to the client
                return web.json_response({"error": str(e)}, status=500)
            if isinstance(result, (dict, list, str, int, float, bool, type(None))):
                return web.json_response({"result": result})
            return web.json_response({"result": repr(result)})

        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handler)
        runner = web.AppRunner(app)
        try:
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, self.options.host, self.options.port)
            loop.run_until_complete(site.start())
        except Exception as e:  # noqa: BLE001 — report to starter
            self._start_error = e
            self._started.set()
            return
        self._runner = runner
        self._started.set()
        loop.run_forever()
        loop.run_until_complete(runner.cleanup())
