"""Shape-bucket helpers shared by the @serve.batch router path and the
LLM engine's continuous-batching scheduler.

Jitted models recompile per distinct input shape, and on TPU a recompile
is tens of seconds of XLA time in the serving hot path (SURVEY.md §7 hard
parts; arxiv 2011.03641 — static-shape batching to stay inside the compile
cache). Everything that submits work to a jitted callable therefore pads
to a CLOSED set of sizes. This module is the one place the padding rule
lives: `serve/batching.py` re-exports `pad_to_bucket` for the decorator
path, and `serve/llm/engine.py` uses it for both batch and sequence-length
dimensions.
"""
from __future__ import annotations

from typing import Sequence


def pad_to_bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= n (last bucket if none fits)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def pow2_buckets(lo: int, hi: int) -> tuple[int, ...]:
    """Ascending powers of two covering [lo, hi]: the default bucket ladder
    for sequence lengths and batch sizes. Bounds the number of distinct
    compiled shapes at log2(hi/lo)+1 per dimension."""
    if lo < 1 or hi < lo:
        raise ValueError(f"need 1 <= lo <= hi, got lo={lo} hi={hi}")
    out = []
    b = 1
    while b < lo:
        b *= 2
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return tuple(out)
